"""Worker-failure injection, detection, and elastic failover.

The reference has straggler *injection* but no failure handling at all: a
dead worker leaves the master's Waitany loop blocked forever (naive waits
for all W, src/naive.py:103-110; AGC waits for num_collect arrivals or full
group coverage, src/approximate_coding.py:144 — both unreachable once too
many workers are gone; README.md:120-122 lists real straggler termination
as unsolved future work). This module closes that gap, TPU-style: failures
are modeled as infinite arrival times in the precomputed schedule, detection
and feasibility analysis are exact host-side checks ahead of the run, and
failover rewrites only the unreachable rounds' collection into a best-effort
unbiased decode over the surviving workers.

Semantics per scheme when workers die (the "would the reference's master
ever exit its wait loop" question):

  naive          any death => hangs forever           src/naive.py:103-110
  cyclic MDS     alive < W-s => hangs                 src/coded.py:137
  FRC            any group fully dead => hangs        src/replication.py:143-155
  AGC            alive < num_collect AND some group
                 fully dead => hangs                  src/approximate_coding.py:144
  avoidstragg    alive < W-s => hangs                 src/avoidstragg.py:106-114
  partial *      any death => hangs (needs ALL
                 uncoded first-parts)                 src/partial_coded.py:174-191

Failover decode (replacing only infeasible rounds):
  uncoded layouts   collect all alive, rescale P/alive — the avoidstragg
                    unbiasedness rescale generalized (src/avoidstragg.py:116)
  FRC layouts       first alive member per group; fully-dead groups are
                    erased, AGC-style (src/approximate_coding.py:155-158)
  MDS layouts       lstsq decode weights over the alive rows of B — exact
                    while alive >= W-s, least-squares best-effort below
  partial layouts   no failover (their uncoded first-parts are structurally
                    required); analyze() reports, plan_run raises
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional

import numpy as np

from erasurehead_tpu.data import sharding as sharding_lib

from erasurehead_tpu.ops import codes
from erasurehead_tpu.ops.codes import CodingLayout
from erasurehead_tpu.parallel import collect
from erasurehead_tpu.utils.config import Scheme

DEAD = np.inf  # a dead worker's arrival time


def inject_worker_death(
    arrivals: np.ndarray, deaths: Mapping[int, int]
) -> np.ndarray:
    """Kill worker w from round r onward: ``deaths = {worker: round}``.

    Fault injection beyond the reference's sleep-based straggling — the
    failure mode its README concedes it never implements (README.md:120-122).
    """
    out = np.array(arrivals, dtype=np.float64, copy=True)
    R = out.shape[0]
    for w, r in deaths.items():
        if not 0 <= w < out.shape[1]:
            raise ValueError(f"worker {w} out of range")
        out[max(0, r):R, w] = DEAD
    return out


def detect_dead(arrivals: np.ndarray, timeout: float) -> np.ndarray:
    """[R, W] bool: workers the master would presume dead — no arrival by
    ``timeout`` simulated seconds into the round.

    The reference cannot express this (its Waitany has no timeout); here it
    is an exact readout of the schedule. ``arrivals`` may also be a
    TELEMETRY worker_times block carrying the reference's ``-1``
    never-collected sentinel (src/coded.py:171-173; the masking rule of
    obs/events.arrival_summary): real arrival times are >= 0 by
    construction, so negative entries mean the master never heard from
    that worker and land on the dead side — feeding raw telemetry here
    must never read ``-1`` as "arrived one second early".
    """
    t = np.asarray(arrivals)
    # non-finite is dead regardless of timeout (inf <= inf would pass a
    # plain comparison); NaN also lands on the dead side, as does the -1
    # never-arrived sentinel (negative = no arrival, not an early one)
    return ~np.isfinite(t) | (t > timeout) | (t < 0.0)


@dataclasses.dataclass(frozen=True)
class FeasibilityReport:
    """Would each round's collection rule ever exit its wait loop?"""

    feasible: np.ndarray  # [R] bool
    dead: np.ndarray  # [R, W] bool (presumed dead per detect_dead)
    scheme: Scheme
    reason: str  # human-readable rule that was applied

    @property
    def all_feasible(self) -> bool:
        return bool(self.feasible.all())

    @property
    def first_infeasible(self) -> Optional[int]:
        bad = np.flatnonzero(~self.feasible)
        return int(bad[0]) if bad.size else None


def analyze(
    scheme: Scheme,
    layout: CodingLayout,
    arrivals: np.ndarray,
    num_collect: int | None = None,
    timeout: float = np.inf,
) -> FeasibilityReport:
    """Per-round feasibility of the scheme's stop condition (table above).

    The per-scheme core lives on the scheme's registry descriptor
    (``feasibility``, erasurehead_tpu/schemes/builtin.py); this wraps it
    with the shared death detection and report plumbing."""
    from erasurehead_tpu import schemes
    from erasurehead_tpu.utils.config import as_scheme

    scheme = as_scheme(scheme)
    desc = schemes.get(scheme)
    dead = detect_dead(arrivals, timeout)
    feasible, reason = desc.feasibility(layout, dead, num_collect=num_collect)
    return FeasibilityReport(
        feasible=np.asarray(feasible), dead=dead, scheme=scheme, reason=reason
    )


class InfeasibleRunError(RuntimeError):
    def __init__(self, report: FeasibilityReport):
        self.report = report
        super().__init__(
            f"scheme {report.scheme.value}: collection unreachable from round "
            f"{report.first_infeasible} ({report.reason}; the reference's "
            "master would block in Waitany forever)"
        )


def failover_schedule(
    schedule: collect.CollectionSchedule,
    layout: CodingLayout,
    arrivals: np.ndarray,
    report: FeasibilityReport,
    timeout: float,
) -> collect.CollectionSchedule:
    """Rewrite infeasible rounds: collect everyone alive at ``timeout``,
    decode best-effort per the layout (module docstring). Feasible rounds
    are untouched — the scheme's own rule already exits there."""
    if report.all_feasible:
        return schedule
    if layout.slot_is_coded is not None and not np.all(layout.slot_is_coded):
        raise InfeasibleRunError(report)  # partial layouts: see docstring
    weights = np.array(schedule.message_weights, copy=True)
    sim = np.array(schedule.sim_time, copy=True)
    wtimes = np.array(schedule.worker_times, copy=True)
    collected = np.array(schedule.collected, copy=True)
    t = np.asarray(arrivals, dtype=np.float64)
    for r in np.flatnonzero(~report.feasible):
        alive = ~report.dead[r]
        collected[r] = alive
        wtimes[r] = np.where(alive, t[r], collect.NEVER)
        sim[r] = timeout
        if layout.B is not None:  # MDS: best-effort lstsq over alive rows
            weights[r] = codes.mds_decode_weights_host(
                layout.B, alive[None, :]
            )[0]
        elif layout.groups is not None:  # FRC/AGC: first alive per group
            win = collect._group_winners(
                np.where(alive, t[r], DEAD)[None, :], layout.groups
            )[0]
            weights[r] = (win & alive).astype(np.float64)
        else:  # uncoded: avoidstragg rescale over survivors
            k = int(alive.sum())
            if k == 0:
                raise InfeasibleRunError(report)
            weights[r] = alive * (layout.n_workers / k)
    return collect.CollectionSchedule(
        message_weights=weights,
        sim_time=sim,
        worker_times=wtimes,
        collected=collected,
    )


def plan_run(
    scheme: Scheme,
    layout: CodingLayout,
    arrivals: np.ndarray,
    num_collect: int | None = None,
    timeout: float = np.inf,
    on_infeasible: str = "error",  # "error" | "failover"
    deadline: float | None = None,
    decode: str = "fixed",
) -> tuple[collect.CollectionSchedule, FeasibilityReport]:
    """Build the run's collection schedule with failure handling.

    ``on_infeasible="error"`` raises InfeasibleRunError where the reference
    would hang; ``"failover"`` degrades those rounds per failover_schedule.
    """
    if on_infeasible == "failover" and not np.isfinite(timeout):
        # failover stamps sim_time[r] = timeout for rewritten rounds; an
        # infinite timeout would silently corrupt every simulated-time view
        # (sim_total_time, plots, time-to-target)
        raise ValueError(
            "on_infeasible='failover' requires a finite timeout "
            f"(got {timeout!r}) — it becomes the rewritten rounds' sim_time"
        )
    report = analyze(scheme, layout, arrivals, num_collect, timeout)
    schedule = collect.build_schedule(
        scheme, arrivals, layout, num_collect=num_collect,
        deadline=deadline, decode=decode,
    )
    if report.all_feasible:
        return schedule, report
    if on_infeasible == "error":
        raise InfeasibleRunError(report)
    if on_infeasible != "failover":
        raise ValueError(f"on_infeasible must be error|failover, got {on_infeasible!r}")
    return (
        failover_schedule(schedule, layout, arrivals, report, timeout),
        report,
    )


def survivor_config(
    cfg,
    n_survivors: int,
    survivor_overrides: Optional[dict] = None,
    lr_schedule=None,
):
    """The survivor-phase RunConfig for ``n_survivors`` workers, validated
    UP FRONT through the scheme registry.

    ``num_collect`` is clamped to W' (a stop count above the worker count
    is unsatisfiable), but clamping alone is not validation: schemes carry
    structural divisibility constraints — FRC's ``(s+1) | W'``
    (src/replication.py:24-26), the partial schemes' partition counts —
    that an unlucky W' violates. Without this check those used to surface
    as an opaque error deep inside layout construction; here the registry
    descriptor's ``validate_config`` runs at config-build time and the
    raised error names ``survivor_overrides`` as the fix (e.g. a smaller
    ``n_stragglers``). ``survivor_overrides`` wins over the derived
    fields, exactly as in :func:`train_elastic`."""
    overrides = dict(
        n_workers=n_survivors,
        num_collect=(
            None
            if cfg.num_collect is None
            else min(cfg.num_collect, n_survivors)
        ),
    )
    if lr_schedule is not None:
        overrides["lr_schedule"] = lr_schedule
    overrides.update(survivor_overrides or {})
    try:
        # RunConfig.__post_init__ delegates to the registry descriptor's
        # validate_config — the single home of scheme invariants
        return dataclasses.replace(cfg, **overrides)
    except ValueError as e:
        raise ValueError(
            f"survivor phase invalid for scheme "
            f"{cfg.scheme.value!r} at W'={n_survivors}: {e}. Pass "
            f"survivor_overrides= adjusting the violated knob (e.g. a "
            f"smaller n_stragglers where FRC requires (s+1) | W')"
        ) from e


@dataclasses.dataclass(frozen=True)
class ElasticReport:
    """What an elastic restart did (train_elastic)."""

    death_round: int  # first round run under the survivor layout
    dead_workers: tuple[int, ...]
    n_workers_before: int
    n_workers_after: int


def train_elastic(
    cfg,
    dataset,
    deaths: Mapping[int, int],
    mesh=None,
    survivor_overrides: Optional[dict] = None,
    measure: bool = True,
    dynamic: bool = False,
):
    """True elastic recovery: re-shard onto the survivors and keep training.

    ``failover_schedule`` degrades the decode of rounds a dead worker makes
    infeasible; this goes further — the capability the reference's README
    concedes it lacks entirely (README.md:120-122, any death hangs its
    master forever). At the earliest death round the run STOPS, the FULL
    dataset re-shards across the surviving worker count under a fresh
    layout of the same scheme, the optimizer state (params + momentum)
    carries over unchanged, and training continues to ``cfg.rounds`` on
    the same lr schedule — so the loss curve is continuous through the
    failure and every partition keeps contributing afterwards (nothing is
    erased, unlike failover's dropped groups; each phase still truncates
    rows to its own partition-count multiple, so up to W-1 tail rows can
    differ between phases — the merged n_train reports the common prefix).

    ``deaths``: {worker_id: round}. All deaths re-shard at the EARLIEST
    round (one restart); workers dying later simply leave earlier. Deaths
    at rounds >= cfg.rounds never occur inside the run and are ignored.
    ``survivor_overrides``: optional RunConfig field overrides for the
    survivor phase (e.g. a smaller n_stragglers when W' breaks the FRC
    divisibility requirement). Returns (TrainResult, ElasticReport); the
    merged artifacts keep the ORIGINAL worker numbering — dead workers'
    columns carry the reference's -1 sentinel after the restart.

    ``dynamic=True`` runs both phases through trainer.train_dynamic — the
    fully on-device control plane (deadline scheme included): the shape an
    online pod scheduler needs when a worker dies mid-run while collection
    decisions live inside the jitted scan.
    """
    import jax

    from erasurehead_tpu.train import trainer

    W = cfg.n_workers
    if not deaths:
        raise ValueError("deaths is empty — nothing to recover from")
    if not all(0 <= w < W for w in deaths):
        raise ValueError(f"dead workers {sorted(deaths)} outside [0, {W})")
    # a death at round >= cfg.rounds never happens inside this run: that
    # worker survives the whole horizon and must NOT be evicted
    effective = {w: r for w, r in deaths.items() if r < cfg.rounds}
    if not effective:
        raise ValueError(
            f"no death occurs before rounds={cfg.rounds}; nothing to recover"
        )
    dead = sorted(effective)
    death_round = min(effective.values())
    if death_round < 1:
        raise ValueError(
            f"earliest death round {death_round} must be in (0, rounds)"
        )
    survivors = [w for w in range(W) if w not in set(dead)]
    W2 = len(survivors)
    if W2 < 1:
        raise ValueError("no survivors")

    # one resolved lr schedule drives both phases (phase 1 takes its
    # prefix) so per-round lr arrays and presets alike stay continuous
    # through the restart
    lr_full = cfg.resolve_lr_schedule()
    # survivor config BEFORE phase 1: an invalid W' (e.g. FRC's (s+1) | W'
    # divisibility) must fail fast with an error naming survivor_overrides,
    # not burn the pre-death phase and then die inside layout construction
    cfg2 = survivor_config(
        cfg, W2, survivor_overrides, lr_schedule=lr_full
    )
    train_fn = trainer.train_dynamic if dynamic else trainer.train
    phase_kw = {} if dynamic else {"measure": measure}
    phase1 = train_fn(
        dataclasses.replace(
            cfg, rounds=death_round, lr_schedule=lr_full[:death_round]
        ),
        dataset,
        mesh=mesh,
        **phase_kw,
    )
    phase2 = train_fn(
        cfg2,
        dataset,
        initial_state=phase1.final_state,
        initial_round=death_round,
        **phase_kw,
    )

    # the phases ran on different meshes (W vs W' divisor device counts):
    # concatenate on host and KEEP the numpy tree — the history's consumers
    # (eval replay, artifacts) pull it to host anyway, so re-uploading
    # [R, ...] x every param leaf to HBM would be pure waste. The fetch is
    # multihost-safe: in a cluster the survivor mesh can exclude some
    # processes' devices entirely (sharding.np_global gathers globally).
    history = jax.tree.map(
        lambda a, b: np.concatenate(
            [sharding_lib.np_global(a), sharding_lib.np_global(b)]
        ),
        phase1.params_history,
        phase2.params_history,
    )
    R = cfg.rounds
    timeset = np.concatenate(
        [phase1.timeset, phase2.timeset[death_round:]]
    )
    # survivor-phase clocks map back to ORIGINAL worker ids; dead columns
    # carry the -1 never-collected sentinel (src/coded.py:171-173)
    wt = -np.ones((R, W))
    col = np.zeros((R, W), dtype=bool)
    wt[:death_round] = phase1.worker_times
    col[:death_round] = phase1.collected
    wt[death_round:, survivors] = phase2.worker_times[death_round:]
    col[death_round:, survivors] = phase2.collected[death_round:]

    result = trainer.TrainResult(
        params_history=history,
        final_params=phase2.final_params,
        timeset=timeset,
        worker_times=wt,
        collected=col,
        sim_total_time=float(timeset.sum()),
        wall_time=phase1.wall_time + phase2.wall_time,
        steps_per_sec=(
            R / (phase1.wall_time + phase2.wall_time)
            if (phase1.wall_time + phase2.wall_time) > 0
            else 0.0
        ),
        # the phases truncate rows to their own partition multiples; the
        # merged loss replay is honest over the common prefix of rows
        n_train=min(phase1.n_train, phase2.n_train),
        config=cfg,
        layout=phase1.layout,
        final_state=phase2.final_state,
    )
    report = ElasticReport(
        death_round=death_round,
        dead_workers=tuple(dead),
        n_workers_before=W,
        n_workers_after=W2,
    )
    return result, report
