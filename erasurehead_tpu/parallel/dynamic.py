"""Fully on-device collection: arrivals, masks, and decode inside the scan.

The default trainer precomputes the whole straggler schedule on host
(float64 control plane, parallel/collect.py) — the exact analogue of the
reference's iteration-seeded, fully predetermined delays. This module is
the *dynamic* alternative: per-round arrival times are drawn with the JAX
counter RNG inside the jitted scan, every collection rule is a fixed-shape
jnp computation, and the MDS decode runs on device
(ops/codes.mds_decode_weights). Nothing touches the host between rounds.

Why it exists: (a) it demonstrates the collection rules survive jit — no
data-dependent Python, no dynamic shapes — which is what makes the design
portable to arrivals *measured* on a real pod rather than simulated; (b) it
is the shape a reactive/online scheduler would take (per-round masks as
traced values). The partial schemes' two-message Waitany replay is a
fixed-shape 2W-event sort + prefix scan (collect_partial_jnp).

Equivalence: every scheme's jnp rule is pinned test-for-test against
parallel/collect.py's numpy event replay on shared arrival matrices
(tests/test_dynamic.py).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from erasurehead_tpu.ops import codes
from erasurehead_tpu.ops.codes import CodingLayout
from erasurehead_tpu.utils.config import Scheme


NEVER = -1.0  # reference sentinel (src/coded.py:171-173; collect.NEVER)


class RoundSchedule(NamedTuple):
    """One round's collection, all traced values."""

    message_weights: jnp.ndarray  # [W]
    sim_time: jnp.ndarray  # scalar
    collected: jnp.ndarray  # [W] bool
    worker_times: jnp.ndarray | None = None  # [W], NEVER for uncollected


def _ranks(t: jnp.ndarray) -> jnp.ndarray:
    """Arrival rank per worker; ties broken by worker index (the
    collect.py `_order` lexsort semantics — argsort is stable)."""
    order = jnp.argsort(t)
    return jnp.zeros_like(order).at[order].set(jnp.arange(t.shape[0]))


def _kth_arrival_time(t: jnp.ndarray, ranks: jnp.ndarray, k: int):
    return jnp.where(ranks == k - 1, t, -jnp.inf).max()


def _group_onehot(groups: np.ndarray) -> np.ndarray:
    G = int(groups.max()) + 1
    return np.eye(G)[groups]  # [W, G]


def collect_all_jnp(t: jnp.ndarray) -> RoundSchedule:
    W = t.shape[0]
    return RoundSchedule(jnp.ones(W), t.max(), jnp.ones(W, bool))


def collect_first_k_mds_jnp(
    t: jnp.ndarray,
    B: jnp.ndarray,
    n_stragglers: int,
    decode_table: codes.MdsDecodeTable | None = None,
) -> RoundSchedule:
    return _first_k_lstsq_jnp(
        t, B, t.shape[0] - n_stragglers, decode_table=decode_table
    )


def _first_k_lstsq_jnp(
    t: jnp.ndarray,
    B: jnp.ndarray,
    k: int,
    decode_table: codes.MdsDecodeTable | None = None,
) -> RoundSchedule:
    """Stop at the k-th arrival, decode over the received rows of B
    (exact MDS for k = W-s; optimal-decoding randreg for k = num_collect).
    With a decode_table, the per-round solve becomes an f64-precomputed
    table gather (safe at any W); otherwise the on-device fp32 solve is
    used (small-W only — see ops/codes.mds_decode_weights)."""
    ranks = _ranks(t)
    mask = ranks < k
    if decode_table is not None:
        weights = decode_table.lookup(mask)
    else:
        weights = codes.mds_decode_weights(B, mask)
    return RoundSchedule(weights, _kth_arrival_time(t, ranks, k), mask)


def collect_avoidstragg_jnp(t: jnp.ndarray, n_stragglers: int) -> RoundSchedule:
    W = t.shape[0]
    k = W - n_stragglers
    ranks = _ranks(t)
    mask = ranks < k
    return RoundSchedule(
        mask * (W / k), _kth_arrival_time(t, ranks, k), mask
    )


def collect_deadline_jnp(t: jnp.ndarray, deadline: float) -> RoundSchedule:
    """Deadline collection (collect.collect_deadline, jnp): take whatever
    arrived by the cutoff, rescale W/collected; zero-arrival rounds apply a
    zero gradient and cost the full deadline."""
    W = t.shape[0]
    mask = t <= deadline
    cnt = mask.sum()
    weights = mask * (W / jnp.maximum(cnt, 1))
    sim = jnp.where(cnt == W, t.max(), deadline)
    return RoundSchedule(weights.astype(jnp.float32), sim, mask)


def collect_agc_jnp(
    t: jnp.ndarray, onehot: jnp.ndarray, num_collect: int
) -> RoundSchedule:
    """AGC stop rule as prefix scans over the arrival order
    (≙ collect.collect_agc's per-event loop, src/approximate_coding.py:144-158)."""
    W, G = onehot.shape
    order = jnp.argsort(t)
    oh_sorted = onehot[order]  # [W, G] rows in arrival order
    cum = jnp.cumsum(oh_sorted, axis=0)
    win_sorted = (oh_sorted * (cum == 1)).sum(axis=1)  # first of its group?
    covered = (cum >= 1).sum(axis=1)  # groups covered after j+1 arrivals
    j1 = jnp.arange(1, W + 1)
    done = (j1 >= num_collect) | (covered >= G)
    stop_idx = jnp.argmax(done)
    taken_sorted = jnp.arange(W) <= stop_idx
    weights = jnp.zeros(W).at[order].set(win_sorted * taken_sorted)
    collected = jnp.zeros(W, bool).at[order].set(taken_sorted)
    return RoundSchedule(weights, t[order[stop_idx]], collected)


def collect_frc_jnp(t: jnp.ndarray, onehot: jnp.ndarray) -> RoundSchedule:
    """FRC == AGC with an unreachable worker quota (collect.collect_frc)."""
    return collect_agc_jnp(t, onehot, num_collect=t.shape[0] + 1)


def collect_partial_jnp(
    t: jnp.ndarray,
    *,
    variant: str,  # "mds" | "frc"
    frac: float,  # uncoded-part send time as a fraction of full compute
    n_stragglers: int = 0,
    B: jnp.ndarray | None = None,  # [W, W], mds variant
    onehot: jnp.ndarray | None = None,  # [W, G], frc variant
    group_ids: jnp.ndarray | None = None,  # [W], frc variant
    decode_table: codes.MdsDecodeTable | None = None,  # mds variant
) -> RoundSchedule:
    """Two-part schemes as a fixed-shape 2W-event sort + prefix scan
    (≙ collect.collect_partial's vectorized replay of the two-message
    Waitany loop, src/partial_coded.py:174-194 /
    src/partial_replication.py:166-187).

    Events 0..W-1 are uncoded parts (arriving at ``frac * t``), events
    W..2W-1 are coded parts (arriving at ``t``); the master's loop exits at
    the first event where all W uncoded parts are in AND the coded-part
    condition holds (>= W-s parts for MDS decode; one part per group for
    FRC). Coded parts processed by then join the decode. MDS weights come
    from the f64-precomputed decode_table when given (completed sets here
    can exceed W-s, which the 0..s multi-pattern table covers); without one,
    the on-device fp32 solve — small-W only (ops/codes.mds_decode_weights)."""
    W = t.shape[0]
    times = jnp.concatenate([frac * t, t])  # [2W]; argsort is stable, so
    order = jnp.argsort(times)  # ties process in (time, part, worker) order
    is_second = order >= W
    cnt_first = jnp.cumsum(~is_second)
    cnt_second = jnp.cumsum(is_second)
    if variant == "mds":
        second_ok = cnt_second >= W - n_stragglers
    elif variant == "frc":
        oh_events = onehot[order % W] * is_second[:, None]  # [2W, G]
        second_ok = (jnp.cumsum(oh_events, axis=0) >= 1).all(axis=1)
    else:
        raise ValueError(f"unknown partial variant {variant!r}")
    done = (cnt_first >= W) & second_ok  # always True at the last event
    stop_idx = jnp.argmax(done)
    sec_taken = is_second & (jnp.arange(2 * W) <= stop_idx)
    completed = (
        jnp.zeros(W, jnp.int32).at[order % W].max(sec_taken.astype(jnp.int32))
        > 0
    )
    if variant == "mds":
        if decode_table is not None:
            weights = decode_table.lookup(completed)
        else:
            weights = codes.mds_decode_weights(B, completed)
    else:
        # each group's first coded arrival, if completed (stable-rank argmin
        # == collect._group_winners' first-index tie-break)
        ranks = _ranks(t)
        min_rank = jnp.min(
            jnp.where(onehot.T.astype(bool), ranks[None, :], W), axis=1
        )  # [G]
        win = ranks == min_rank[group_ids]
        weights = (win & completed).astype(t.dtype)
    return RoundSchedule(weights, times[order[stop_idx]], completed)


def make_round_schedule_fn(
    scheme: Scheme,
    layout: CodingLayout,
    num_collect: int | None = None,
    delay_mean: float = 0.5,
    add_delay: bool = True,
    deadline: float | None = None,
) -> Callable[[jax.Array], RoundSchedule]:
    """(per-round key) -> RoundSchedule, fully traceable.

    The arrival model matches straggler.jax_delay_schedule (threefry
    exponential draws; not bit-matched to the reference's MT19937 — use the
    host control plane for run-for-run numeric parity with the reference).

    The per-scheme rule comes from the scheme's registry descriptor
    (``dynamic_rule``, erasurehead_tpu/schemes/builtin.py): each factory
    closes over its layout tables and — for the MDS family — precomputes
    the f64 decode table so the in-scan decode is a table gather, immune
    to the fp32 conditioning hazard at canonical W=30
    (ops/codes.MdsDecodeTable; falls back with a warning past the table
    cap).
    """
    from erasurehead_tpu import schemes

    desc = schemes.get(scheme)
    W = layout.n_workers
    if desc.dynamic_rule is None:
        raise ValueError(
            f"scheme {desc.name!r} has no dynamic (on-device) collection "
            "rule; use the host control plane (trainer.train)"
        )
    rule = desc.dynamic_rule(layout, num_collect=num_collect, deadline=deadline)

    def draw(key):
        if not add_delay:
            return jnp.zeros(W)
        return delay_mean * jax.random.exponential(key, (W,))

    def schedule(key: jax.Array) -> RoundSchedule:
        t = draw(key)
        rs = rule(t)
        return rs._replace(
            worker_times=jnp.where(rs.collected, t, NEVER)
        )

    return schedule
