"""Straggler injection: seeded per-iteration arrival-delay schedules.

The reference injects stragglers by making every worker sleep an
Exponential(mean 0.5s) delay after computing its gradient, with the numpy
global RNG re-seeded to the iteration index so the whole delay matrix is
deterministic and identical on every rank (src/naive.py:140-149, identical
block in every scheme file). That replayability is the backbone of its
AGC-vs-EGC-vs-uncoded comparisons: every scheme sees the *same* straggler
schedule.

On a lockstep SPMD TPU there is nothing to sleep — every chip computes every
iteration. Straggling instead enters as a simulated *arrival time* per
(iteration, worker): collection rules turn arrivals into completion masks and
simulated wall-clock (SURVEY.md §5.8). This module produces those arrival
matrices:

  - :func:`reference_delay_schedule` reproduces the reference's exact numbers
    (same MT19937 streams) so time curves are comparable run-for-run.
  - :func:`jax_delay_schedule` is the native path (threefry counter RNG),
    usable on-device for dynamic schedules.

Both are *schedules known ahead of the run* — exactly as in the reference,
where seeding by iteration index makes the future fully predetermined. The
framework exploits this to precompute decode weights on host (float64 control
plane) while the gradient data plane runs on TPU.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np


def reference_delay_schedule(
    rounds: int, n_workers: int, mean: float = 0.5, seed_offset: int = 0
) -> np.ndarray:
    """[rounds, n_workers] delay matrix, bit-exact with the reference.

    The reference executes ``np.random.seed(i); np.random.exponential(0.5,
    n_workers)`` inside iteration i (src/naive.py:141-147);
    ``np.random.RandomState(i).exponential`` draws from the identical MT19937
    stream. ``seed_offset`` selects an independent delay universe with the
    same construction (0 = the reference's own schedule) — the variance
    study's knob (tools/flagship_variance.py), kept here so the
    reference-fidelity recipe has exactly one home.
    """
    out = np.empty((rounds, n_workers))
    for i in range(rounds):
        out[i] = np.random.RandomState(i + seed_offset).exponential(
            mean, n_workers
        )
    return out


def jax_delay_schedule(
    key: jax.Array, rounds: int, n_workers: int, mean: float = 0.5
) -> jnp.ndarray:
    """Native JAX exponential delay schedule (not bit-matched to numpy)."""
    keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(jnp.arange(rounds))
    return jax.vmap(
        lambda k: mean * jax.random.exponential(k, (n_workers,))
    )(keys)


@dataclasses.dataclass(frozen=True)
class ArrivalModel:
    """Turns injected delays into per-(round, worker) arrival times.

    arrival = compute_time + delay. The reference's worker_timeset also
    includes gradient compute + network transfer on top of the sleep; by
    default we model a uniform ``compute_time`` of 0 (pure delay ordering —
    the regime the reference's experiments are in, where the 0.5s-mean sleeps
    dominate ~ms matvecs). A nonzero compute_time or per-worker speed factors
    model heterogeneous clusters.
    """

    compute_time: float = 0.0
    worker_speed: np.ndarray | None = None  # [W] multiplier on compute_time

    def arrivals(self, delays: np.ndarray) -> np.ndarray:
        base = self.compute_time
        if self.worker_speed is not None:
            base = self.compute_time * np.asarray(self.worker_speed)[None, :]
        return np.asarray(delays) + base


def model_from_config(cfg) -> "ArrivalModel | None":
    """ArrivalModel for a RunConfig's heterogeneity fields (None when the
    config is in the reference's pure-delay regime)."""
    if not cfg.compute_time and not cfg.worker_speed_spread:
        return None
    speed = None
    if cfg.worker_speed_spread:
        rng = np.random.default_rng(cfg.seed + 10_007)
        s = float(cfg.worker_speed_spread)
        speed = rng.uniform(1.0 - s, 1.0 + s, cfg.n_workers)
    return ArrivalModel(compute_time=cfg.compute_time, worker_speed=speed)


@dataclasses.dataclass(frozen=True)
class RegimeShift:
    """A deterministic mid-run change of the straggler regime.

    The reference's delay model is stationary (the same Exponential(0.5)
    stream every round); the worst-case analyses the retrieved papers run
    are not — "Fundamental Limits of Approximate Gradient Coding"
    (arXiv:1901.08166) shows the cost of straggling concentrates in
    adversarial/non-stationary patterns. Three kinds:

      - ``"heavytail"``: Exponential(mean) delays through round
        ``round``-1, then Pareto(``alpha``)-tailed delays (seeded per
        round like the reference's own stream, so the whole matrix stays
        deterministic and shared across schemes). Small ``alpha`` =
        heavier tail; alpha <= 1 has infinite mean — every round pays
        some worker's catastrophic delay.
      - ``"adversary"``: from round ``round`` on, worker ``worker`` turns
        adversarially slow (+``slowdown`` simulated seconds on top of its
        drawn delay) — the fixed-straggler worst case of 1901.08166,
        where any scheme that must hear from that worker stalls every
        round.
      - ``"targeted"``: from round ``round`` on, EVERY replica of coded
        partition group ``group`` turns slow at once (+``slowdown`` each)
        — 1901.08166's worst case for fractional-repetition codes, where
        replication buys nothing because the adversary slows the whole
        replica set instead of one worker. The attacked worker set is
        derived from the run's layout (:func:`targeted_workers`: all
        workers holding partition ``group`` — for FRC exactly the
        partition's repetition group), so the same ``slowdown`` budget
        spread over unrelated workers leaves every group a fast member
        while the targeted form stalls one group every round
        (test-pinned: targeted hurts repcoded more than a uniform attack
        of equal total budget).

    This is what the adapt/ controller reacts to: a policy tuned to the
    pre-shift regime stops being the best arm at ``round``.
    """

    kind: str  # "heavytail" | "adversary" | "targeted"
    round: int  # first round of the new regime
    alpha: float = 1.2  # heavytail: Pareto tail index
    worker: int = 0  # adversary: which worker turns slow
    slowdown: float = 5.0  # adversary/targeted: extra seconds per round
    group: int = 0  # targeted: which coded partition group is attacked

    def __post_init__(self):
        if self.kind not in ("heavytail", "adversary", "targeted"):
            raise ValueError(
                f"regime kind must be heavytail/adversary/targeted, "
                f"got {self.kind!r}"
            )
        if self.round < 0:
            raise ValueError(f"regime round must be >= 0, got {self.round}")
        if self.kind == "heavytail" and self.alpha <= 0:
            raise ValueError(f"heavytail alpha must be > 0, got {self.alpha}")
        if self.kind in ("adversary", "targeted") and self.slowdown < 0:
            raise ValueError(
                f"{self.kind} slowdown must be >= 0, got {self.slowdown}"
            )
        if self.kind == "targeted" and self.group < 0:
            raise ValueError(
                f"targeted group must be >= 0, got {self.group}"
            )


#: seed offset separating the post-shift heavy-tail stream from the
#: reference's own exponential stream (which seeds RandomState(i))
_REGIME_SEED_BASE = 104_729


def targeted_workers(layout, group: int) -> tuple[int, ...]:
    """The worker set a ``"targeted"`` regime attacks: every worker
    holding partition ``group % P`` of ``layout`` — for fractional
    repetition exactly the members of that partition's repetition group
    (all its replicas, the pattern arXiv:1901.08166 proves worst-case for
    FRC), and for any other layout the partition's full replica set."""
    assignment = np.asarray(layout.assignment)
    p = int(group) % int(layout.n_partitions)
    workers = np.flatnonzero((assignment == p).any(axis=1))
    if workers.size == 0:
        raise ValueError(
            f"targeted regime: no worker holds partition {p} of layout "
            f"{layout.name!r} — nothing to attack"
        )
    return tuple(int(w) for w in workers)


def apply_regime_shift(
    delays: np.ndarray,
    shift: RegimeShift,
    mean: float = 0.5,
    workers=None,
) -> np.ndarray:
    """Rewrite a [R, W] delay matrix's rounds >= shift.round per the shift
    (deterministic: heavy-tail rounds re-seed per round exactly like
    :func:`reference_delay_schedule`, so every scheme in a paired sweep
    sees the identical shifted stream). ``workers`` is the resolved
    attacked set for the ``"targeted"`` kind (:func:`targeted_workers` —
    the caller resolves it because only the caller holds the layout)."""
    out = np.array(delays, dtype=np.float64, copy=True)
    R, W = out.shape
    r0 = min(max(int(shift.round), 0), R)
    if shift.kind == "heavytail":
        for i in range(r0, R):
            rs = np.random.RandomState(_REGIME_SEED_BASE + i)
            # Pareto(alpha) - shifted to start at 0, scaled so the
            # pre-shift mean survives as the scale unit; alpha near 1
            # makes the per-round max routinely 10-100x the mean
            out[i] = mean * rs.pareto(shift.alpha, W)
    elif shift.kind == "adversary":
        out[r0:, shift.worker % W] += shift.slowdown
    elif shift.kind == "targeted":
        if workers is None:
            raise ValueError(
                "targeted regime shift needs the resolved attacked worker "
                "set (straggler.targeted_workers(layout, group)); the "
                "delay matrix alone cannot name a coded group"
            )
        idx = np.asarray(sorted(int(w) % W for w in workers), dtype=int)
        out[r0:, idx] += shift.slowdown
    return out


def load_arrival_trace(trace) -> np.ndarray:
    """A recorded per-round arrival-time trace as a float64 [R, W] matrix.

    ``trace`` is an array (validated and passed through) or a path:
    ``.npy`` / ``.npz`` (an ``arrivals`` entry, else the first array) /
    anything else is read as whitespace/comma-delimited text, one round
    per line. A 1-D trace is a single round. Values are per-(round,
    worker) arrival delays in simulated seconds; negative entries are
    refused (the collection rules' time axis starts at 0)."""
    if isinstance(trace, (str, bytes)):
        path = str(trace)
        if path.endswith(".npy"):
            arr = np.load(path)
        elif path.endswith(".npz"):
            with np.load(path) as z:
                key = "arrivals" if "arrivals" in z.files else z.files[0]
                arr = z[key]
        else:
            arr = np.loadtxt(path, delimiter="," if path.endswith(".csv") else None)
    else:
        arr = trace
    arr = np.asarray(arr, dtype=np.float64)
    if arr.ndim == 1:
        arr = arr[None, :]
    if arr.ndim != 2 or arr.size == 0:
        raise ValueError(
            f"arrival trace must be a non-empty [rounds, workers] matrix, "
            f"got shape {arr.shape}"
        )
    if (arr < 0).any():
        raise ValueError("arrival trace has negative arrival times")
    return arr


def replay_arrival_trace(
    trace, rounds: int, n_workers: int, speed: np.ndarray | None = None
) -> np.ndarray:
    """Tile a recorded trace (:func:`load_arrival_trace`) over ``rounds``
    rounds, with an optional [W] per-worker speed multiplier on every row
    (heterogeneous replay: worker w's recorded delays scale by
    ``speed[w]``). The trace's worker count must match the run's — a
    silently broadcast mismatch would replay the wrong cluster."""
    arr = load_arrival_trace(trace)
    if arr.shape[1] != n_workers:
        raise ValueError(
            f"arrival trace has {arr.shape[1]} workers but the run has "
            f"{n_workers}; record and replay must agree"
        )
    reps = -(-rounds // arr.shape[0])  # ceil
    out = np.tile(arr, (reps, 1))[:rounds]
    if speed is not None:
        speed = np.asarray(speed, dtype=np.float64)
        if speed.shape != (n_workers,) or (speed <= 0).any():
            raise ValueError(
                f"trace speed multipliers must be [W] positives, got "
                f"{speed!r}"
            )
        out = out * speed[None, :]
    return out


def arrival_schedule(
    rounds: int,
    n_workers: int,
    add_delay: bool,
    mean: float = 0.5,
    arrival_model: ArrivalModel | None = None,
    regime: RegimeShift | None = None,
    trace=None,
    trace_speed: np.ndarray | None = None,
    regime_workers=None,
) -> np.ndarray:
    """The full [rounds, W] arrival-time matrix for a run.

    With ``add_delay=False`` the reference's workers reply in compute order
    with no injected sleep (main.py arg add_delay, src/naive.py:140); we model
    that as all-zero arrivals (ties broken by worker index in the collection
    rules, documented there). ``regime`` applies a deterministic mid-run
    straggler-regime change (:class:`RegimeShift`) on top of the drawn
    delays — the adversary kind applies even with delays off (a slow
    worker is slow whether or not the exponential stream is injected).

    ``trace`` replaces the drawn delay stream with a recorded per-round
    trace (path or array; :func:`replay_arrival_trace` — tiled over
    ``rounds``, ``trace_speed`` scales each worker's recorded delays),
    replacing i.i.d.-exponential-only injection with real cluster replay;
    ``add_delay`` is ignored (the trace IS the delay schedule) while
    ``regime`` and the ``arrival_model`` compute terms still compose on
    top, so heterogeneity studies run against recorded streams too.

    ``regime_workers`` is the resolved attacked worker set for a
    ``"targeted"`` regime (:func:`targeted_workers`); like the adversary
    kind, a targeted attack applies even with delays off (a slowed group
    is slow whether or not the exponential stream is injected)."""
    if trace is not None:
        delays = replay_arrival_trace(trace, rounds, n_workers, trace_speed)
    elif add_delay:
        delays = reference_delay_schedule(rounds, n_workers, mean)
    else:
        delays = np.zeros((rounds, n_workers))
    if regime is not None and (
        add_delay
        or trace is not None
        or regime.kind in ("adversary", "targeted")
    ):
        delays = apply_regime_shift(delays, regime, mean, regime_workers)
    model = arrival_model or ArrivalModel()
    return model.arrivals(delays)
