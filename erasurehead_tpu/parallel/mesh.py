"""Device mesh construction for the coded-DP worker axis.

The reference's parallelism is a master + W workers as MPI ranks over
ethernet (SURVEY.md §2.2). Here the W *logical* workers live on a 1-D
``jax.sharding.Mesh`` axis ("workers"): each device holds W/n_devices
workers' (possibly redundant) partition stacks, gradients reduce over the
axis with ``psum`` riding ICI (multi-host: DCN via jax.distributed — see
parallel/backend.py). There is no master device: the decode is replicated,
its inputs are tiny, and XLA keeps it fused with the update.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORKER_AXIS = "workers"
# tensor-parallel axis: the MLP family's hidden dimension splits over it
# (models/mlp._predict_tp); same 2-D-mesh composition pattern as the
# sequence axis (parallel/ring.SEQ_AXIS)
MODEL_AXIS = "model"


def ring_order_devices(devices: Sequence) -> list:
    """Order devices so consecutive mesh positions are physical ICI
    neighbors (boustrophedon / snake walk over the chip coordinates), so
    the ring collectives this axis carries — the stack-mode="ring"
    ppermute hops (parallel/step._ring_fill) and ring attention
    (parallel/ring.py) — ride single-hop ICI links instead of hashing
    across the torus.

    Backends without chip coordinates (CPU test meshes, the forced-host
    driver meshes) keep the given order — the alignment is a TPU locality
    optimization, never a semantic change (mesh position, not device id,
    defines the logical ring everywhere).
    """
    devs = list(devices)
    coords = []
    for d in devs:
        c = getattr(d, "coords", None)
        if c is None:
            return devs
        coords.append(tuple(c) + (int(getattr(d, "core_on_chip", 0) or 0),))
    dims = max(len(c) for c in coords)
    coords = [c + (0,) * (dims - len(c)) for c in coords]
    span = [sorted({c[i] for c in coords}) for i in range(dims)]

    def snake_key(c):
        # nested snake: dimension i+1 runs backward whenever the traversal
        # position in dimension i is odd, so successive keys differ by one
        # coordinate step
        key, flip = [], False
        for i in range(dims):
            pos = span[i].index(c[i])
            kpos = (len(span[i]) - 1 - pos) if flip else pos
            key.append(kpos)
            flip ^= kpos % 2 == 1
        return tuple(key)

    order = sorted(range(len(devs)), key=lambda k: snake_key(coords[k]))
    return [devs[k] for k in order]


def worker_mesh(
    n_devices: Optional[int] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """1-D mesh over the worker axis, ring-aligned (see ring_order_devices).

    ``n_devices`` trims to a prefix of the available devices (useful when the
    logical worker count W must divide the device count's multiple).
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"asked for {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.asarray(ring_order_devices(devs)), (WORKER_AXIS,))


def worker_plus_axis_mesh(
    axis_name: str,
    shards: int,
    workers_devices: int,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """2-D mesh (workers, <axis>): coded-DP over dim 0 composed with a
    second model-internal parallelism axis over dim 1. Row stacks shard
    over ``workers`` and replicate over the second axis; the model splits
    its own internal dimension over it (token axis for seq, hidden units
    for tensor parallelism) and psums where the math requires."""
    devs = list(devices if devices is not None else jax.devices())
    need = workers_devices * shards
    if need > len(devs):
        raise ValueError(
            f"mesh {workers_devices}x{shards} needs {need} devices, "
            f"have {len(devs)}"
        )
    grid = np.asarray(devs[:need]).reshape(workers_devices, shards)
    return Mesh(grid, (WORKER_AXIS, axis_name))


def worker_seq_mesh(
    seq_shards: int,
    workers_devices: int,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """(workers, seq): sequence parallelism for the attention family
    (parallel/ring.py's axis; models/attention._predict_seq)."""
    from erasurehead_tpu.parallel.ring import SEQ_AXIS

    return worker_plus_axis_mesh(SEQ_AXIS, seq_shards, workers_devices, devices)


def worker_tp_mesh(
    tp_shards: int,
    workers_devices: int,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """(workers, model): tensor parallelism for the MLP family — hidden
    units split over the model axis (models/mlp._predict_tp)."""
    return worker_plus_axis_mesh(MODEL_AXIS, tp_shards, workers_devices, devices)


def axis_active(mesh: Mesh, axis_name: str) -> bool:
    """Does this mesh carry a >1-sized ``axis_name`` axis? The single rule
    the model families' ``for_mesh`` hooks use to decide whether to swap
    in their model-parallel variant."""
    return axis_name in mesh.axis_names and mesh.shape[axis_name] > 1


def worker_sharding(mesh: Mesh) -> NamedSharding:
    """Shard dim 0 (the worker / partition axis) across the mesh's worker
    axis; any other mesh axes (seq) replicate."""
    return NamedSharding(mesh, P(WORKER_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def check_divisible(n: int, mesh: Mesh, what: str) -> None:
    # the sharded axis is WORKER_AXIS; other axes (seq) replicate the data
    d = (
        mesh.shape[WORKER_AXIS]
        if WORKER_AXIS in mesh.axis_names
        else mesh.devices.size
    )
    if n % d:
        raise ValueError(
            f"{what}={n} must be divisible by the mesh's {d} worker-axis "
            f"devices; pick n_workers as a multiple of the device count"
        )
