"""Device mesh construction for the coded-DP worker axis.

The reference's parallelism is a master + W workers as MPI ranks over
ethernet (SURVEY.md §2.2). Here the W *logical* workers live on a 1-D
``jax.sharding.Mesh`` axis ("workers"): each device holds W/n_devices
workers' (possibly redundant) partition stacks, gradients reduce over the
axis with ``psum`` riding ICI (multi-host: DCN via jax.distributed — see
parallel/backend.py). There is no master device: the decode is replicated,
its inputs are tiny, and XLA keeps it fused with the update.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORKER_AXIS = "workers"


def worker_mesh(
    n_devices: Optional[int] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """1-D mesh over the worker axis.

    ``n_devices`` trims to a prefix of the available devices (useful when the
    logical worker count W must divide the device count's multiple).
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"asked for {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (WORKER_AXIS,))


def worker_sharding(mesh: Mesh) -> NamedSharding:
    """Shard dim 0 (the worker / partition axis) across the mesh."""
    return NamedSharding(mesh, P(WORKER_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def check_divisible(n: int, mesh: Mesh, what: str) -> None:
    d = mesh.devices.size
    if n % d:
        raise ValueError(
            f"{what}={n} must be divisible by the mesh's {d} devices; "
            f"pick n_workers as a multiple of the device count"
        )
