"""Ring attention: sequence-parallel exact attention over a 1-D device mesh.

Beyond-parity capability (the reference has no sequence dimension anywhere —
SURVEY.md §2.2/§5.7): this is the TPU-native long-context primitive the
coded-DP framework composes with when a model DOES have a sequence axis.
Each device holds one contiguous shard of the sequence; K/V shards rotate
around the ring with ``lax.ppermute`` (neighbor hops riding ICI) while the
local Q shard folds every visiting block into a flash-style online softmax
(running row-max + normalizer), so the full [T, T] score matrix never
materializes on any chip and per-chip memory stays O(T/N · d + (T/N)²).

Design notes (TPU-first):
  - the N rotation steps are a ``lax.scan`` — one compiled block program,
    no per-step Python, and XLA overlaps each hop's ppermute with the
    previous block's compute;
  - blockwise online-softmax accumulation is the blockwise-parallel
    formulation of exact attention (numerically identical to softmax(QKᵀ)V
    up to f32 reduction order);
  - causal masking uses global positions derived from ``lax.axis_index``,
    so the same program is correct for any shard count without host logic.

API: :func:`ring_attention` acts on per-device shards under ``shard_map``
(use :func:`make_ring_attention_fn` for the sharded entry point).
"""

from __future__ import annotations

from functools import partial

import jax
from erasurehead_tpu.utils import compat
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from erasurehead_tpu.utils.compat import shard_map

SEQ_AXIS = "seq"
_NEG_INF = -1e30  # additive mask value; finite so exp() never NaNs


def _block_update(acc, m, l, scores, v_blk):
    """Fold one visiting K/V block into the online-softmax state.

    acc: [Tq, d] unnormalized output; m: [Tq] running row max;
    l: [Tq] running normalizer; scores: [Tq, Tk]; v_blk: [Tk, d].
    """
    m_new = jnp.maximum(m, scores.max(axis=1))
    # rescale previous state to the new max, then add this block
    corr = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[:, None])
    l_new = l * corr + p.sum(axis=1)
    acc_new = acc * corr[:, None] + p @ v_blk
    return acc_new, m_new, l_new


def ring_attention_shard(
    q: jnp.ndarray,  # [Tq, d] this device's query shard
    k: jnp.ndarray,  # [Tk, d] this device's key shard
    v: jnp.ndarray,  # [Tk, d] this device's value shard
    *,
    axis_name: str = SEQ_AXIS,
    causal: bool = False,
    scale: float | None = None,
) -> jnp.ndarray:
    """Exact attention for this device's queries against the FULL sequence.

    Runs the N-step ring under ``lax.scan``: at step s the local K/V buffer
    holds the shard originally owned by device (idx - s) mod N; ppermute
    passes buffers to the next ring position each step.
    """
    n = compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    Tq, d = q.shape
    Tk = k.shape[0]
    scale = (d ** -0.5) if scale is None else scale
    in_dtype = q.dtype
    q = q.astype(jnp.float32) * scale

    # global positions for causal masking (shards are contiguous slices)
    q_pos = idx * Tq + jnp.arange(Tq)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, s):
        acc, m, l, k_buf, v_buf = carry
        # k_buf currently holds the shard of device (idx - s) mod n
        owner = (idx - s) % n
        scores = q @ k_buf.astype(jnp.float32).T  # [Tq, Tk]
        if causal:
            k_pos = owner * Tk + jnp.arange(Tk)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask, scores, _NEG_INF)
        acc, m, l = _block_update(acc, m, l, scores, v_buf.astype(jnp.float32))
        # rotate for the next step (the final rotation restores ownership)
        k_buf = lax.ppermute(k_buf, axis_name, perm)
        v_buf = lax.ppermute(v_buf, axis_name, perm)
        return (acc, m, l, k_buf, v_buf), None

    # initial accumulators are constants, but every later carry value varies
    # across the mesh (it depends on axis_index and on q/k/v). Deriving the
    # zeros from q makes them inherit q's exact varying-axes set, keeping
    # the scan carry type stable under shard_map's vma checking on ANY
    # enclosing mesh — a seq-only mesh here, or the trainer's 2-D
    # (workers, seq) mesh where the data varies over both axes.
    acc0 = q * 0.0  # [Tq, d] f32 (q was upcast above)
    m0 = q[:, 0] * 0.0 + _NEG_INF
    l0 = q[:, 0] * 0.0
    (acc, m, l, _, _), _ = lax.scan(
        step, (acc0, m0, l0, k, v), jnp.arange(n)
    )
    # fully-masked rows (none exist for causal contiguous shards, but keep
    # the division total) normalize to 0 rather than NaN
    return (acc / jnp.maximum(l, 1e-30)[:, None]).astype(in_dtype)


def make_ring_attention_fn(mesh: Mesh, *, causal: bool = False):
    """Sharded entry point: [T, d] arrays sequence-sharded over ``mesh``'s
    single axis; returns the exact attention output with the same sharding.
    """
    (axis_name,) = mesh.axis_names

    fn = shard_map(
        partial(ring_attention_shard, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), P(axis_name)),
        out_specs=P(axis_name),
    )
    return jax.jit(fn)


def ulysses_attention_shard(
    q: jnp.ndarray,  # [Tq, H, d] this device's sequence shard, all heads
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str = SEQ_AXIS,
    causal: bool = False,
    scale: float | None = None,
) -> jnp.ndarray:
    """All-to-all ("Ulysses") sequence parallelism: the other canonical SP
    pattern. Instead of rotating K/V around a ring, ONE all_to_all over the
    stacked [3, T/N, H, d] q/k/v re-shards sequence-sharded inputs into
    head-sharded full sequences [3, T, H/N, d], each chip runs plain
    attention for its own heads, and a second all_to_all restores sequence
    sharding — two collectives total per call vs the ring's N ppermute
    hops. Cheaper on all-to-all-friendly fabrics when H is divisible by
    the axis size; the ring wins when T is long and H is small. Both
    produce exact attention; tests pin them to each other and the oracle.
    """
    n = compat.axis_size(axis_name)
    H = q.shape[1]
    if H % n:
        raise ValueError(f"heads={H} must be divisible by axis size {n}")

    # tiled=True: split/concat within the existing axes instead of
    # inserting a new leading device dimension
    qkv = jnp.stack([q, k, v])  # [3, T/N, H, d]
    qh, kh, vh = lax.all_to_all(
        qkv, axis_name, split_axis=2, concat_axis=1, tiled=True
    )  # [3, T, H/N, d]
    per_head = jax.vmap(
        partial(reference_attention, causal=causal, scale=scale),
        in_axes=1,
        out_axes=1,
    )
    return lax.all_to_all(
        per_head(qh, kh, vh), axis_name, split_axis=0, concat_axis=1,
        tiled=True,
    )


def make_ulysses_attention_fn(mesh: Mesh, *, causal: bool = False):
    """Sharded entry point: [T, H, d] arrays sequence-sharded on dim 0."""
    (axis_name,) = mesh.axis_names
    fn = shard_map(
        partial(ulysses_attention_shard, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), P(axis_name)),
        out_specs=P(axis_name),
    )
    return jax.jit(fn)


def reference_attention(q, k, v, *, causal: bool = False, scale=None):
    """Single-device oracle: softmax(QKᵀ/√d)V with optional causal mask."""
    d = q.shape[-1]
    scale = (d ** -0.5) if scale is None else scale
    scores = (q.astype(jnp.float32) * scale) @ k.astype(jnp.float32).T
    if causal:
        T, Tk = scores.shape
        mask = jnp.arange(T)[:, None] >= jnp.arange(Tk)[None, :]
        scores = jnp.where(mask, scores, _NEG_INF)
    w = jax.nn.softmax(scores, axis=1)
    return (w @ v.astype(jnp.float32)).astype(q.dtype)
