"""Collection rules: who the master hears from, what weights decode the gradient.

In the reference, each scheme's master sits in an ``MPI.Request.Waitany`` loop
with a scheme-specific stop condition, stamping per-worker arrival latencies
and then decoding from whoever made it (SURVEY.md §2.3). On TPU that
asynchronous ragged protocol becomes a *pure function of arrival times*: given
the simulated arrivals ``t[round, worker]`` (parallel/straggler.py), each rule
computes, ahead of the training scan and in float64 on host,

  - ``message_weights`` [R, W]: the decode coefficient applied to each
    worker's transmitted (coded) message — 0 for uncollected/unused workers;
  - ``sim_time`` [R]: the simulated master wall-clock for the iteration (the
    reference's ``timeset``, src/naive.py:95,126);
  - ``worker_times`` [R, W]: per-worker arrival stamps with the reference's
    -1 sentinel for workers never collected (src/coded.py:171-173);
  - ``collected`` [R, W]: who the master heard from at all.

This is the control plane: tiny arrays, exact float64, fully precomputed —
mirroring how the reference's iteration-seeded delays predetermine every
arrival. The data plane (the gradient einsum against these weights) runs
jitted on the mesh (parallel/step.py). An online on-device variant of the MDS
rule exists for dynamic arrivals (ops/codes.mds_decode_weights) with
documented fp32 limits.

Stop conditions being reproduced (file:line into /root/reference):
  naive          wait for all W workers                src/naive.py:103-110
  cyclic MDS     first W-s arrivals, lstsq decode      src/coded.py:137-149
  FRC            first arrival of every group          src/replication.py:143-155
  AGC            num_collect arrivals OR all groups    src/approximate_coding.py:144-158
  avoidstragg    first W-s, unbiasedness rescale       src/avoidstragg.py:106-116
  partial MDS    all uncoded parts AND >= W-s coded    src/partial_coded.py:174-194
  partial FRC    all uncoded parts AND 1 coded/group   src/partial_replication.py:166-187

Tie-breaking: arrivals are processed in ascending (t, worker_index) order —
continuous delays make exact ties measure-zero; with delays disabled
(all-zero arrivals) this degrades deterministically to worker-index order.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from erasurehead_tpu.ops import codes
from erasurehead_tpu.ops.codes import CodingLayout
from erasurehead_tpu.utils.config import Scheme

NEVER = -1.0  # reference sentinel for "not collected" (src/coded.py:171-173)


@dataclasses.dataclass(frozen=True)
class CollectionSchedule:
    """Per-round decode control data (see module docstring)."""

    message_weights: np.ndarray  # [R, W] float64
    sim_time: np.ndarray  # [R] float64
    worker_times: np.ndarray  # [R, W] float64, NEVER sentinel
    collected: np.ndarray  # [R, W] bool


def _order(t: np.ndarray) -> np.ndarray:
    """Arrival processing order per round: ascending time, worker index
    tie-break. Stable argsort == lexsort((index, t)); accepts [W] or [R, W]."""
    return np.argsort(t, axis=-1, kind="stable")


def _rank(t: np.ndarray) -> np.ndarray:
    """[R, W] arrival rank of each worker within its round."""
    R, W = t.shape
    ranks = np.empty((R, W), dtype=np.int64)
    np.put_along_axis(
        ranks, _order(t), np.broadcast_to(np.arange(W), (R, W)), axis=1
    )
    return ranks


def _group_winners(t: np.ndarray, groups: np.ndarray) -> np.ndarray:
    """[R, W] bool: is worker the earliest arrival of its group (index tie-break)."""
    R, W = t.shape
    n_groups = int(groups.max()) + 1
    win = np.zeros((R, W), dtype=bool)
    for g in range(n_groups):
        members = np.flatnonzero(groups == g)
        best = members[np.argmin(t[:, members], axis=1)]  # argmin: first index wins
        win[np.arange(R), best] = True
    return win


def _stamp(t: np.ndarray, collected: np.ndarray) -> np.ndarray:
    return np.where(collected, t, NEVER)


def collect_all(t: np.ndarray) -> CollectionSchedule:
    """Uncoded synchronous GD: master waits for everyone (src/naive.py:103-110)."""
    R, W = t.shape
    return CollectionSchedule(
        message_weights=np.ones((R, W)),
        sim_time=t.max(axis=1),
        worker_times=t.copy(),
        collected=np.ones((R, W), dtype=bool),
    )


def _first_k_lstsq(t: np.ndarray, B: np.ndarray, k: int) -> CollectionSchedule:
    """Stop at the k-th arrival, lstsq-decode over the received rows of B."""
    ranks = _rank(t)
    collected = ranks < k
    weights = codes.mds_decode_weights_host(B, collected)
    kth_time = np.where(ranks == k - 1, t, -np.inf).max(axis=1)
    return CollectionSchedule(
        message_weights=weights,
        sim_time=kth_time,
        worker_times=_stamp(t, collected),
        collected=collected,
    )


def collect_first_k_mds(
    t: np.ndarray, B: np.ndarray, n_stragglers: int
) -> CollectionSchedule:
    """Exact MDS coding: stop at the first W-s arrivals, solve decode weights
    over exactly that set (src/coded.py:137-149)."""
    return _first_k_lstsq(t, B, t.shape[1] - n_stragglers)


def collect_frc(t: np.ndarray, groups: np.ndarray) -> CollectionSchedule:
    """Fractional repetition: wait until every group has reported once; use
    each group's first arrival, ignore (but stamp) earlier-processed
    non-first arrivals (src/replication.py:143-155).

    Implemented as AGC with an unreachable worker quota: the stop condition
    degenerates to "all groups covered", giving identical event-order
    semantics (including deterministic tie-breaking by worker index when
    arrivals tie, e.g. with delays disabled)."""
    return collect_agc(t, groups, num_collect=t.shape[1] + 1)


def collect_agc(
    t: np.ndarray, groups: np.ndarray, num_collect: int
) -> CollectionSchedule:
    """Approximate gradient coding: process arrivals until either
    ``num_collect`` workers have reported or every group is covered; sum the
    first arrival of each covered group; groups with no arrival among those
    processed are *erased* from the gradient
    (src/approximate_coding.py:144-158).

    Vectorized over rounds: all R Waitany replays run as one batched
    argsort + prefix-scan (no per-round Python — the control plane stays
    sub-second at R=10,000, tests/test_collect.py)."""
    R, W = t.shape
    n_groups = int(groups.max()) + 1
    order = _order(t)  # [R, W] event processing order
    onehot = np.eye(n_groups, dtype=np.int64)[np.asarray(groups)]  # [W, G]
    oh_sorted = onehot[order]  # [R, W, G] group membership in arrival order
    cum = np.cumsum(oh_sorted, axis=1)
    # first arrival of its group among events processed so far?
    win_sorted = (oh_sorted * (cum == 1)).sum(axis=2)  # [R, W] 0/1
    covered = (cum >= 1).sum(axis=2)  # [R, W] groups covered after j+1 events
    j = np.arange(1, W + 1)
    done = (j >= num_collect) | (covered >= n_groups)
    stop_idx = done.argmax(axis=1)  # first index where the loop exits
    taken_sorted = np.arange(W) <= stop_idx[:, None]
    weights = np.zeros((R, W))
    np.put_along_axis(weights, order, win_sorted * taken_sorted, axis=1)
    collected = np.zeros((R, W), dtype=bool)
    np.put_along_axis(collected, order, taken_sorted, axis=1)
    stop_worker = np.take_along_axis(order, stop_idx[:, None], axis=1)
    sim = np.take_along_axis(t, stop_worker, axis=1)[:, 0]
    return CollectionSchedule(
        message_weights=weights,
        sim_time=sim,
        worker_times=_stamp(t, collected),
        collected=collected,
    )


def collect_first_k_optimal(
    t: np.ndarray, B: np.ndarray, num_collect: int
) -> CollectionSchedule:
    """Optimal-decoding AGC (beyond the reference; arXiv 2006.09638 via
    PAPERS.md): stop at the first ``num_collect`` arrivals and take the
    least-squares-optimal combination of their messages — the weights
    minimizing ||w^T B - 1||_2 over the received rows of the incidence
    matrix. Exact when the received rows span the all-ones vector;
    otherwise the minimum-error approximate gradient (vs FRC-AGC's
    all-or-nothing group erasures)."""
    return _first_k_lstsq(t, B, num_collect)


def collect_avoidstragg(t: np.ndarray, n_stragglers: int) -> CollectionSchedule:
    """Ignore-stragglers baseline: sum the first W-s uncoded gradients and
    rescale by W/(W-s) for unbiasedness — the reference folds the rescale
    into grad_multiplier = lr / (n_samples*(W-s)/W) (src/avoidstragg.py:116)."""
    R, W = t.shape
    k = W - n_stragglers
    ranks = _rank(t)
    collected = ranks < k
    kth_time = np.where(ranks == k - 1, t, -np.inf).max(axis=1)
    return CollectionSchedule(
        message_weights=collected * (W / k),
        sim_time=kth_time,
        worker_times=_stamp(t, collected),
        collected=collected,
    )


def collect_deadline(t: np.ndarray, deadline: float) -> CollectionSchedule:
    """Deadline-based collection (beyond the reference): the master takes
    every gradient that arrived by ``deadline`` simulated seconds into the
    round and rescales by W/collected for unbiasedness (the avoidstragg
    rescale, src/avoidstragg.py:116, with a data-dependent count). A round
    where ALL workers arrive early stops at the last arrival; otherwise
    the master must wait out the full deadline (it cannot know nothing
    else is coming). A round with zero arrivals applies a zero gradient
    and costs the deadline — inherently failure-tolerant: dead workers
    (t = inf) simply never make the cutoff.
    """
    R, W = t.shape
    collected = t <= deadline
    cnt = collected.sum(axis=1)
    weights = collected * (W / np.maximum(cnt, 1)[:, None])
    all_in = cnt == W
    sim = np.where(all_in, t.max(axis=1, initial=-np.inf), deadline)
    return CollectionSchedule(
        message_weights=weights,
        sim_time=sim,
        worker_times=_stamp(t, collected),
        collected=collected,
    )


def collect_partial(
    t: np.ndarray,
    layout: CodingLayout,
    variant: str,  # "mds" | "frc"
) -> CollectionSchedule:
    """Two-part schemes: every worker sends its uncoded part when its unique
    partitions are done, its coded part when the rest are; the master needs
    ALL uncoded parts plus enough coded parts (W-s for MDS decode
    src/partial_coded.py:174-194; one per group for FRC
    src/partial_replication.py:166-187).

    Timing model: a worker's full compute finishes at t[r, w]; its uncoded
    part (n_sep of n_slots partitions) is sent at the same fraction of that
    time. ``message_weights`` here weight only the *coded* messages — the
    step applies weight 1.0 to separate slots unconditionally
    (CodingLayout.slot_is_coded).
    """
    R, W = t.shape
    s = layout.n_stragglers
    t_first, t_second = layout.uncoded_frac * t, t
    # Event-based replay of the two-message Waitany loop: 2W events per round
    # (each worker's uncoded part at t_first, coded part at t_second),
    # processed in ascending (time, part, worker) order — deterministic under
    # ties (delays disabled). The loop exits at the first event satisfying
    # BOTH stop conditions; coded parts processed by then join the decode.
    times = np.concatenate([t_first, t_second], axis=1)  # [R, 2W]; first W = uncoded
    order = _order(times)  # stable: ascending (time, part, worker)
    is_second = order >= W  # [R, 2W]: is the j-th processed event a coded part?
    cnt_first = np.cumsum(~is_second, axis=1)
    cnt_second = np.cumsum(is_second, axis=1)
    if variant == "mds":
        second_ok = cnt_second >= W - s
    else:
        # one coded part per group (partial FRC): per-event group coverage
        onehot = np.eye(layout.n_groups, dtype=np.int64)[
            np.asarray(layout.groups)
        ]  # [W, G]
        oh_events = onehot[order % W] * is_second[..., None]  # [R, 2W, G]
        second_ok = (np.cumsum(oh_events, axis=1) >= 1).all(axis=2)
    done = (cnt_first >= W) & second_ok  # always True at the last event
    stop_idx = done.argmax(axis=1)  # loop exits at the first such event
    stop_ev = np.take_along_axis(order, stop_idx[:, None], axis=1)
    stop = np.take_along_axis(times, stop_ev, axis=1)[:, 0]
    # coded parts processed up to and including the stop event join the decode
    sec_taken = is_second & (np.arange(2 * W) <= stop_idx[:, None])
    completed = np.zeros((R, W), dtype=bool)
    rr, jj = np.nonzero(sec_taken)
    completed[rr, order[rr, jj] % W] = True
    if variant == "mds":
        # the reference solves over ALL completed coded parts at loop exit
        # (src/partial_coded.py:192-193 — possibly more than W-s rows)
        weights = codes.mds_decode_weights_host(layout.B, completed)
    elif variant == "frc":
        # only each group's first coded arrival is summed
        # (src/partial_replication.py:173-180)
        win = _group_winners(t_second, layout.groups)
        weights = (win & completed).astype(np.float64)
    else:
        raise ValueError(f"unknown partial variant {variant!r}")
    # reference worker_timeset: stamped per message, then overwritten with -1
    # for workers whose coded part never arrived (src/partial_coded.py:210-212)
    return CollectionSchedule(
        message_weights=weights,
        sim_time=stop,
        worker_times=_stamp(t_second, completed),
        collected=completed,
    )


def optimal_decode_weights_host(E: np.ndarray, masks: np.ndarray) -> np.ndarray:
    """Least-squares collection weights fit to the ACTUAL arrival sets —
    the optimal decoder of "Approximate Gradient Coding with Optimal
    Decoding" (arXiv:2006.09638).

    ``E`` is the layout's [W, P] effective coding matrix
    (CodingLayout.effective_matrix: message_w = E[w] @ partition_grads);
    for each round's completion mask the returned row minimizes
    ``||w^T E - 1||_2`` over weights supported on the collected workers —
    exactly the weight-space decode error obs/decode.py surfaces, so
    per round this decode is the minimum-error linear combination of
    whatever actually arrived (vs e.g. AGC's all-or-nothing group
    erasures or the avoidstragg/deadline uniform rescales).

    Host float64, batched over rounds; like the MDS solver above, each
    DISTINCT mask is solved once (a cohort's [R, W] mask batch shares the
    handful of patterns the straggler regime produces — the "tiny [k, P]
    solve, batchable across a cohort" of ROADMAP item 1/5).
    """
    E = np.asarray(E, dtype=np.float64)
    masks = np.asarray(masks, dtype=bool)
    ones = np.ones(E.shape[1])
    uniq, inverse = np.unique(masks, axis=0, return_inverse=True)
    out = np.zeros((uniq.shape[0], E.shape[0]))
    for k in range(uniq.shape[0]):
        live = np.flatnonzero(uniq[k])
        if live.size:
            out[k, live] = np.linalg.lstsq(E[live, :].T, ones, rcond=None)[0]
    return out[inverse.reshape(-1)]


def optimal_decode_schedule(
    schedule: CollectionSchedule, layout: CodingLayout
) -> CollectionSchedule:
    """``decode="optimal"``: keep the schedule's stop condition — who was
    collected, when the master exited — and refit only the decode weights
    to each round's actual arrival set (:func:`optimal_decode_weights_host`).
    Timing artifacts (sim_time, worker_times, collected) are untouched:
    the optimal decoder changes what the master does WITH the messages,
    never how long it waits for them."""
    weights = optimal_decode_weights_host(
        layout.effective_matrix(), schedule.collected
    )
    return dataclasses.replace(schedule, message_weights=weights)


def build_schedule(
    scheme: Scheme,
    t: np.ndarray,
    layout: CodingLayout,
    num_collect: int | None = None,
    deadline: float | None = None,
    decode: str = "fixed",
) -> CollectionSchedule:
    """Build the scheme's collection schedule via its registry descriptor
    (erasurehead_tpu/schemes/; the reference's dispatch was main.py:62-92).

    ``decode="optimal"`` refits the decode weights per round to the
    actual arrival pattern (:func:`optimal_decode_schedule`) on schemes
    whose descriptor carries an ``optimal_decode`` hook; schemes without
    one (the partial two-part layouts) keep their fixed weights.
    """
    from erasurehead_tpu import schemes

    desc = schemes.get(scheme)
    sched = desc.build_schedule(
        t, layout, num_collect=num_collect, deadline=deadline
    )
    if decode == "optimal" and desc.optimal_decode is not None:
        sched = desc.optimal_decode(sched, layout)
    elif decode not in ("fixed", "optimal"):
        raise ValueError(f"decode must be fixed/optimal, got {decode!r}")
    return sched
