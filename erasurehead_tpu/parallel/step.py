"""The coded gradient step: shard_map SPMD over the worker mesh axis.

This replaces the reference's entire MPI hot loop (SURVEY.md §2.3): the
per-iteration Isend fan-out of beta, each worker's redundant partial-gradient
compute, the Waitany partial gather, and the master-side decode
(src/approximate_coding.py:122-207 and counterparts) become one jitted SPMD
program:

  - the model params are replicated (the reference broadcast them per
    iteration; under jit replication is free — there is no repeated transfer),
  - each device computes the slot gradients of its shard of logical workers
    (faithful mode) or partitions (deduped mode) — batched matmuls that XLA
    tiles onto the MXU,
  - decode = a weighted contraction against the collection weights followed
    by a single ``psum`` over the worker axis riding ICI — the masked
    equivalent of "sum the first k arrivals, scaled by the decode
    coefficients".

Straggler semantics live entirely in the *weights* (parallel/collect.py):
a worker whose message the master never used contributes with weight 0. On a
lockstep SPMD machine every chip computes every iteration regardless; what
gradient coding buys there is captured by the simulated-time accounting, and
honestly reported as such (BASELINE.md).
"""

from __future__ import annotations

import os
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from erasurehead_tpu.parallel.mesh import WORKER_AXIS
from erasurehead_tpu.utils import compat
from erasurehead_tpu.utils.compat import shard_map
from erasurehead_tpu.utils.tracing import annotate

GradFn = Callable[..., Any]  # (params, X, y, weights) -> gradient pytree


def _dq(local_body: GradFn) -> GradFn:
    """Dequantize a compressed stack (ops/features.QuantizedStack) at the
    top of a per-device grad body: the int8 payload + scale table stream
    from HBM, the f32 reconstruction is an on-chip temporary, and every
    local lowering downstream (per-slot vmap, flat, margin-flat, cohort
    matmul) sees the same dense array an uncompressed run would. Identity
    (and free) for ordinary stacks — every shard_map factory wraps its
    body exactly once, so compressed stacks compose with all transports
    and lowerings without per-path plumbing."""
    from erasurehead_tpu.ops import features as features_lib

    def local(params, Xs, ys, ws):
        return local_body(params, features_lib.maybe_dequantize(Xs), ys, ws)

    return local


def _weighted_tree_sum(weights: jnp.ndarray, grads: Any, contract: str) -> Any:
    """sum_i weights[i...] * grads[i...] over the leading axes of each leaf."""
    return jax.tree.map(
        lambda G: jnp.einsum(
            f"{contract},{contract}...->...",
            weights.astype(G.dtype),
            G,
            precision=lax.Precision.HIGHEST,
        ),
        grads,
    )


def _vma_check(model):
    """shard_map replication-check setting for a grad body: on jax 0.4.x
    the checker cannot trace replication through the grads-via-loss
    models' AD (the explicit recipe in _weighted_loss_grad makes the
    output replicated in fact) — disable it there; None keeps the
    version default everywhere else."""
    if _grads_via_loss(model) and not compat.IMPLICIT_REPLICATED_GRAD_PSUM:
        return False
    return None


def _grads_via_loss(model) -> bool:
    """Autodiff models (MLP/attention — MarginClassifierBase) must NOT have
    per-slot jax.grad calls under the shard_map: differentiating w.r.t. the
    replicated params implicitly psums cotangents across the mesh (the vma
    rule that a replicated primal's cotangent is the mesh-wide sum), so the
    per-slot-grads + weighted-contraction + explicit-psum pipeline the
    closed-form GLMs use would double-count — and under vmap the implicit
    psum runs per slot POSITION, silently mixing different workers' slots.
    These models instead expose the weighted scalar loss and take ONE
    jax.grad per device, letting the implicit psum produce the global
    decoded gradient directly (no explicit psum)."""
    return getattr(model, "grads_via_loss", False)


def _weighted_loss_grad(model, params, Xs, ys, ws, contract: str, mesh=None):
    """grad of sum_slots w_slot * loss(params, X_slot, y_slot) over THIS
    device's slots; under the vma system (jax >= 0.6) the implicit
    replicated-param psum makes the result the mesh-global decoded
    gradient, replicated. On jax 0.4.x there is no implicit psum: the
    standalone recipe from the model families' ``grad_sum`` docstrings is
    applied explicitly — scale the loss by 1/(model-internal axis sizes),
    then psum over EVERY mesh axis (replicated-path leaves arrive
    full-per-member and the psum undoes the scaling; partitioned-path
    leaves arrive as member slices and the psum assembles them; the
    worker axis carries disjoint data shards that the psum sums)."""
    nvmap = len(contract)  # "ws" = [Wl, S, ...] stacks, "p" = [Pl, ...]

    def L(p):
        per = model.loss_sum
        for _ in range(nvmap):
            per = jax.vmap(per, in_axes=(None, 0, 0))
        return jnp.sum(ws.astype(jnp.float32) * per(p, Xs, ys))

    g = jax.grad(L)(params)
    if not compat.IMPLICIT_REPLICATED_GRAD_PSUM:
        axes = tuple(mesh.axis_names) if mesh is not None else (WORKER_AXIS,)
        denom = 1
        for a in axes:
            if a != WORKER_AXIS:
                denom *= mesh.shape[a]
        g = jax.tree.map(
            lambda l: lax.psum(l / denom if denom > 1 else l, axes), g
        )
    return g


# Whether margin_flat="auto" resolves to the hybrid lowering for dense
# closed-form stacks. False pending its end-to-end race
# (dense_f32_marginflat, tools/tpu_measurements_flat.sh); the profile
# evidence behind the hybrid: the flat 2-D margin matmul measured 1.587 ms
# vs the batched per-slot contraction's 1.843 at [90, 4400, 128], while
# the batched transpose is near-free (two_pass 1.909 vs margin_only
# 1.843) and the FLAT transpose is catastrophic (the flat-everything step
# halved end-to-end throughput, dense_f32_flat).
MARGIN_FLAT_DEFAULT = False


def supports_margin_flat(model, X) -> bool:
    """The hybrid needs a closed-form GLM on a DENSE stack: the margin
    lowers as one flat 2-D matmul while the transpose stays the batched
    per-slot contraction (sparse stacks have their own margin paths).
    A QuantizedStack is a dense stack in int8 clothing — the body
    dequantizes first (_dq), so the dense lowerings apply."""
    from erasurehead_tpu.ops import features as features_lib

    return (
        hasattr(model, "margin_residual")
        and not _grads_via_loss(model)
        and isinstance(X, (jax.Array, features_lib.QuantizedStack))
    )


def resolve_margin_flat(margin_flat: str, model, X) -> bool:
    if not supports_margin_flat(model, X):
        return False
    if margin_flat == "on":
        return True
    if margin_flat == "off":
        return False
    return MARGIN_FLAT_DEFAULT


def _hybrid_margin_flat_grad(model, params, Xs, ys, ws):
    """Flat 2-D margin matmul + batched per-slot weighted transpose — the
    two measured winners combined (see MARGIN_FLAT_DEFAULT). Works for
    both the worker-major [Wl, S, rows, F] and partition-major
    [Pl, rows, F] stacks: leading axes flatten into one slot axis M.
    Same math as the per-slot vmap; only reduction order differs."""
    from erasurehead_tpu.ops import features as features_lib

    R = ys.shape[-1]
    F = Xs.shape[-1]
    M = int(np.prod(ys.shape[:-1]))
    X3 = Xs.reshape(M, R, F)
    p = features_lib.matvec(Xs.reshape(M * R, F), params)
    r = model.margin_residual(p, ys.reshape(M * R))
    wr = ws.reshape(M)[:, None] * r.reshape(M, R)
    if X3.dtype == jnp.bfloat16 and wr.dtype != X3.dtype:
        # bf16 DATA mode: stream X as stored, cast the small operand down,
        # accumulate f32 on the MXU (same rule as features.rmatvec)
        return -jnp.einsum(
            "mrf,mr->f", X3, wr.astype(X3.dtype),
            preferred_element_type=jnp.float32,
        )
    return -jnp.einsum(
        "mrf,mr->f", X3, wr,
        precision=features_lib.get_default_precision(),
    )


def _margin_flat_local_body(model) -> GradFn:
    """Per-device body of the hybrid lowering (see make_margin_flat_grad_fn);
    also reusable as the ring transport's local grad (make_ring_faithful_grad_fn)."""

    def local(params, Xs, ys, ws):
        with annotate("eh_step/partial_grads"):
            g = _hybrid_margin_flat_grad(model, params, Xs, ys, ws)
        with annotate("eh_step/decode"):
            return lax.psum(g, WORKER_AXIS)

    return local


def make_margin_flat_grad_fn(model, mesh: Mesh) -> GradFn:
    """The hybrid lowering as a whole-grad_fn swap (the _apply_flat_grad
    pattern): drop-in for make_faithful_grad_fn (worker-major
    [Wl, S, rows, F]) and make_deduped_grad_fn (partition-major
    [Pl, rows, F]) on dense closed-form stacks — leading axes flatten
    into one slot axis either way. Caller gates on supports_margin_flat.
    """

    return shard_map(
        _dq(_margin_flat_local_body(model)),
        mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS), P(WORKER_AXIS)),
        out_specs=P(),
    )


def _faithful_local_body(model, mesh: Mesh) -> GradFn:
    """Per-device body of the faithful per-slot step: slot gradients of
    this device's workers, weighted contraction, psum decode. Shared by
    make_faithful_grad_fn (materialized stacks) and
    make_ring_faithful_grad_fn (ring-reconstructed buffers) so the two
    stack modes can never drift numerically."""

    def local(params, Xw, yw, slot_weights):
        if _grads_via_loss(model):
            with annotate("eh_step/partial_grads"):
                return _weighted_loss_grad(
                    model, params, Xw, yw, slot_weights, "ws", mesh
                )
        with annotate("eh_step/partial_grads"):
            per_slot = jax.vmap(
                jax.vmap(lambda X, y: model.grad_sum(params, X, y))
            )(Xw, yw)  # leaves [Wl, S, ...]
        with annotate("eh_step/decode"):
            g = _weighted_tree_sum(slot_weights, per_slot, "ws")
            return lax.psum(g, WORKER_AXIS)

    return local


def make_faithful_grad_fn(model, mesh: Mesh) -> GradFn:
    """Every logical worker computes all of its (redundant) slot gradients.

    Matches the reference's cost model: an FRC/MDS worker really does
    (s+1) partitions' worth of matvec work each iteration
    (src/approximate_coding.py:194-196 over the stacked X_current).

    Args of the returned fn:
      params: replicated pytree.
      Xw, yw: worker-major stacks [W, S, rows, F] / [W, S, rows] (leaves of
        PaddedRows likewise lead with [W, S, ...]), sharded on dim 0.
      slot_weights: [W, S] decode x coding weight per slot message.
    Returns the decoded gradient pytree, replicated.
    """

    return shard_map(
        _dq(_faithful_local_body(model, mesh)),
        mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS), P(WORKER_AXIS)),
        out_specs=P(),
        check_vma=_vma_check(model),
    )


def _ring_fill(plan, Xp, yp, pipeline: bool = False):
    """Inside the shard_map body: reconstruct this device's worker-major
    slot buffer [Wl, S, rows, ...] from the partition-major local shard
    [Pl, rows, ...] via ``plan.n_hops - 1`` lax.ppermute neighbor hops.

    Hop 0 copies from the device's own block; each further hop rotates the
    visiting partition block one ring position forward (device d receives
    device d+1's block, the direction the cyclic codes' w..w+s supports
    point) and scatters whatever slots that block owns into the buffer.
    The buffer is a per-step temporary — the (s+1)x redundancy never
    becomes persistent HBM. Values are moved, never transformed, and the
    fill order is identical in both modes, so the downstream
    slot-gradient contraction sees bit-identical inputs to the
    materialized stack's.

    Transport scheduling, per ``pipeline`` (cfg.ring_pipeline):

    - ``False`` (sequential): each scan step runs ``ppermute -> fill`` in
      order — the fill CONSUMES the ppermute's output, so the data
      dependence serializes every ICI transfer behind the previous fill
      and XLA cannot overlap them. This is the original transport; it
      issues exactly ``n_hops - 1`` ppermutes.
    - ``True`` (double-buffered): the ppermute for hop t+1 is issued in
      the scan carry BEFORE hop t's block is filled — the fill reads the
      block that already arrived, the next transfer has no consumer
      inside this step, and XLA is free to fly hop t+1's ICI traffic
      under hop t's fill/scatter. A prologue issues hop 1 before hop 0's
      (communication-free) own-block fill, and an epilogue fills the last
      block without issuing a dead transfer — still exactly
      ``n_hops - 1`` ppermutes, same bytes on the wire.
    """
    D, H = plan.n_devices, plan.n_hops
    idx = lax.axis_index(WORKER_AXIS)
    sel_dev = jnp.asarray(plan.sel)[idx]  # [H, Wl, S], this device's plan
    perm = [(i, (i - 1) % D) for i in range(D)]
    ppermute = lambda blk: jax.tree.map(
        lambda l: lax.ppermute(l, WORKER_AXIS, perm), blk
    )

    def fill(buf, blk, sel_h):
        take = jnp.where(sel_h >= 0, sel_h, 0)  # [Wl, S] safe gather index

        def one(buf_leaf, blk_leaf):
            cand = blk_leaf[take]  # [Wl, S, rows, ...]
            mask = (sel_h >= 0).reshape(
                sel_h.shape + (1,) * (cand.ndim - 2)
            )
            # buf=None on the first fill: the background is cand*0 so the
            # buffer inherits the data's exact varying-axes set (the scan
            # carry type must be stable under shard_map's vma checking —
            # same trick as parallel/ring.py's accumulator init)
            prev = cand * 0 if buf_leaf is None else buf_leaf
            return jnp.where(mask, cand, prev)

        if buf is None:
            return jax.tree.map(lambda b: one(None, b), blk)
        return jax.tree.map(one, buf, blk)

    with annotate("eh_step/ring_fill"):
        blk = (Xp, yp)
        if pipeline and H > 1:
            # software-pipelined: hop 1's transfer departs before hop 0's
            # own-block fill; each scan step fills the block in hand while
            # the next is in flight; the epilogue fill issues no transfer
            blk_next = ppermute(blk)
            buf = fill(None, blk, sel_dev[0])
            if H > 2:

                def hop(carry, sel_h):
                    buf, blk_cur = carry
                    blk_nxt = ppermute(blk_cur)
                    return (fill(buf, blk_cur, sel_h), blk_nxt), None

                (buf, blk_next), _ = lax.scan(
                    hop, (buf, blk_next), sel_dev[1:-1]
                )
            return fill(buf, blk_next, sel_dev[H - 1])
        buf = fill(None, blk, sel_dev[0])
        if H > 1:

            def hop(carry, sel_h):
                buf, blk = carry
                blk = ppermute(blk)
                return (fill(buf, blk, sel_h), blk), None

            (buf, _), _ = lax.scan(hop, (buf, blk), sel_dev[1:])
        return buf


# Whether ring_pipeline="auto" resolves to the double-buffered transport.
# False pending its end-to-end race (dense_f32_ringpipe / dense_int8_ringpipe,
# tools/tpu_measurements_rep2.sh): the pipelined schedule moves the same
# bytes over the same hops in the same fill order (bitwise-pinned either
# way, tests/test_ring_stack.py), so the only question is whether XLA
# actually flies hop t+1's ICI transfer under hop t's fill on real
# silicon — a question this repo answers with a tagged measurement, not a
# default flip on faith (the FLAT_GRAD_DEFAULT precedent: profile-favored
# lowerings have lost end-to-end races here before).
RING_PIPELINE_DEFAULT = False


def resolve_ring_pipeline(ring_pipeline: str, model=None, X=None) -> bool:
    """Should a ring-transport run use the double-buffered schedule?
    "on"/"off" force; "auto" resolves cached tune decision -> hardcoded
    fallback: a ``ring_pipeline`` race verdict in the tune decision cache
    (erasurehead_tpu/tune/) at this run's shape wins, else
    :data:`RING_PIPELINE_DEFAULT` (measurement-pinned module state). The
    resolution is keyed into the executable cache via the trainer's
    resolved ring signature so neither a default flip nor a cache update
    can ever serve a stale program. ``model``/``X`` give the consult its
    shape signature; without them the resolver is the bare constant (the
    pre-tune behavior)."""
    if ring_pipeline == "on":
        return True
    if ring_pipeline == "off":
        return False
    if model is not None and X is not None:
        from erasurehead_tpu import tune as tune_lib

        choice = tune_lib.lookup(
            "ring_pipeline", tune_lib.run_shape_signature(model, X),
            fallback="pipelined" if RING_PIPELINE_DEFAULT else "sequential",
        )
        if choice is not None:
            return choice == "pipelined"
    return RING_PIPELINE_DEFAULT


def make_ring_faithful_grad_fn(
    model, mesh: Mesh, plan, local_body: GradFn = None, check_vma=None,
    pipeline: bool = False,
) -> GradFn:
    """Faithful-mode decoded gradient from the PARTITION-major stack
    (stack_mode="ring"): per-step ring transport (:func:`_ring_fill`)
    rebuilds each device's [Wl, S, rows, ...] slot buffer, then the SAME
    local grad body as the materialized mode computes and contracts the
    slot gradients in canonical slot order — trajectories are bitwise
    identical to materialized faithful; only the transport differs.

    Args of the returned fn:
      params: replicated pytree.
      Xp, yp: partition-major stacks [P, rows, ...] / [P, rows], sharded.
      slot_weights: [W, S] decode x coding weight per slot message.
    ``local_body`` swaps in an alternative per-device grad body (the flat /
    margin-flat lowerings) — it receives the reconstructed worker-major
    buffer exactly as the materialized fn would. ``pipeline`` picks the
    double-buffered transport schedule (see :func:`_ring_fill`); the fill
    order and values are identical either way, so the choice is a pure
    lowering knob (resolve_ring_pipeline).
    """
    body = _dq(local_body or _faithful_local_body(model, mesh))

    def local(params, Xp, yp, slot_weights):
        Xw, yw = _ring_fill(plan, Xp, yp, pipeline=pipeline)
        return body(params, Xw, yw, slot_weights)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS), P(WORKER_AXIS)),
        out_specs=P(),
        check_vma=_vma_check(model) if check_vma is None else check_vma,
    )


def _deduped_local_body(model, mesh: Mesh) -> GradFn:
    """Per-device body of the deduped per-partition step; shared by
    make_deduped_grad_fn and the cohort-batched factory
    (make_cohort_grad_fn) so the two dispatch shapes can never drift."""

    def local(params, Xp, yp, part_weights):
        if _grads_via_loss(model):
            with annotate("eh_step/partial_grads"):
                return _weighted_loss_grad(
                    model, params, Xp, yp, part_weights, "p", mesh
                )
        with annotate("eh_step/partial_grads"):
            per_part = jax.vmap(
                lambda X, y: model.grad_sum(params, X, y)
            )(Xp, yp)
        with annotate("eh_step/decode"):
            g = _weighted_tree_sum(part_weights, per_part, "p")
            return lax.psum(g, WORKER_AXIS)

    return local


# Whether layer_coding="auto" resolves to the blockwise per-layer decode
# for supported models. False pending its end-to-end race (the repo's
# measurement-pinned-default rule: deep_cohort rows in BASELINE.md race it
# explicitly; the blockwise decode is bitwise-identical to the treewise
# decode — tests/test_deep_coding.py — so the knob is a pure lowering
# choice, forceable per run with layer_coding="on").
LAYER_CODING_DEFAULT = False


def supports_layer_coding(model) -> bool:
    """Can this model's gradients take the per-layer (blockwise) decode
    path (:func:`_layer_block_local_body`)?

    Two exclusions, both structural:
      - autodiff families under a vma-checking jax (>= 0.6): per-slot
        ``jax.grad`` w.r.t. replicated params inside shard_map implicitly
        psums cotangents per slot position there (see _grads_via_loss) —
        the blockwise body's per-slot grads would double-count. On jax
        0.4.x there is no implicit psum and the per-slot form is exact.
      - model-internal mesh axes (seq/tp/pp/ep): those route gradients
        through _weighted_loss_grad's multi-axis psum recipe; the
        blockwise body decodes over the worker axis only.
    """
    if _grads_via_loss(model) and compat.IMPLICIT_REPLICATED_GRAD_PSUM:
        return False
    for ax in ("seq_axis", "tp_axis", "pp_axis", "ep_axis"):
        if getattr(model, ax, None) is not None:
            return False
    return True


def resolve_layer_coding(layer_coding: str, model, X=None) -> bool:
    """Should this run decode per layer block? ("on" validity is the
    caller's concern — this resolves the choice, it does not raise.)
    "auto" resolves cached tune decision -> hardcoded fallback: a
    ``layer_coding`` race verdict at this run's shape (erasurehead_tpu/
    tune/) wins over :data:`LAYER_CODING_DEFAULT`; ``X`` gives the
    consult its shape signature."""
    if not supports_layer_coding(model):
        return False
    if layer_coding == "on":
        return True
    if layer_coding == "off":
        return False
    if X is not None:
        from erasurehead_tpu import tune as tune_lib

        choice = tune_lib.lookup(
            "layer_coding", tune_lib.run_shape_signature(model, X),
            fallback="blockwise" if LAYER_CODING_DEFAULT else "treewise",
        )
        if choice is not None:
            return choice == "blockwise"
    return LAYER_CODING_DEFAULT


# Whether the blockwise decode's "auto" lowering takes the FUSED per-leaf
# contraction (ops/kernels.fused_block_decode — no materialized
# [M, L, width] grad table) or the original treewise pack-then-einsum
# body. False pending its races: the CPU verdict lands in the tune
# decision cache via `make tune-smoke`/bench, the TPU verdict via the
# fused_decode tags in tools/tpu_measurements*.sh — defaults flip through
# data, not code edits (the FLAT_GRAD_DEFAULT rule).
BLOCK_DECODE_FUSED_DEFAULT = False


def resolve_block_decode(block_decode: str, model=None, X=None) -> bool:
    """Should a blockwise (layer-coding) run decode through the fused
    per-leaf kernel instead of the treewise table einsum?

    Resolution order (explicit > env > measured > hardcoded):
      1. ``block_decode`` = "fused"/"treewise" forces;
      2. ``ERASUREHEAD_BLOCK_DECODE`` env forces (operator escape hatch);
      3. a cached ``block_decode`` tune race verdict at this run's shape;
      4. :data:`BLOCK_DECODE_FUSED_DEFAULT`.
    Both paths are bitwise-identical (tests/test_deep_coding.py pins
    them), so this is a pure lowering choice — but it IS keyed into
    lowering_signature so executable caches fork on it."""
    if block_decode == "fused":
        return True
    if block_decode == "treewise":
        return False
    env = os.environ.get("ERASUREHEAD_BLOCK_DECODE", "")
    if env in ("fused", "treewise"):
        return env == "fused"
    if model is not None and X is not None:
        from erasurehead_tpu import tune as tune_lib

        choice = tune_lib.lookup(
            "block_decode", tune_lib.run_shape_signature(model, X),
            fallback="fused" if BLOCK_DECODE_FUSED_DEFAULT else "treewise",
        )
        if choice is not None:
            return choice == "fused"
    return BLOCK_DECODE_FUSED_DEFAULT


def _layer_block_local_body(model, spec, contract: str) -> GradFn:
    """Per-device body of the per-layer (blockwise) coded step.

    Each slot/partition gradient is computed as a pytree (exactly as the
    per-slot default does), packed into the model's padded ``[L, width]``
    block table (ops/blocks.py — DeepMLP layers and MoE expert shards are
    individual rows), and decoded with ONE batched einsum
    ``[..., P] x [..., P, L, width] -> [L, width]`` — a small per-block
    contraction instead of a per-leaf gather-and-combine over the full
    pytree, which is what keeps decode cost flat as depth grows. Values
    are moved, never transformed: the blockwise decode is BITWISE
    identical to :func:`_weighted_tree_sum` over the same grads
    (tests/test_deep_coding.py pins it), so the knob is a pure lowering
    choice.

    ``contract`` is "ws" (faithful worker-major stacks) or "p" (deduped
    partition-major stacks), mirroring the default bodies."""
    from erasurehead_tpu.ops import blocks as blocks_lib

    def local(params, Xs, ys, ws):
        per = lambda X, y: blocks_lib.tree_to_blocks(
            model.grad_sum(params, X, y), spec
        )
        for _ in range(len(contract)):
            per = jax.vmap(per)
        with annotate("eh_step/partial_grads"):
            table = per(Xs, ys)  # [..., L, width]
        with annotate("eh_step/decode"):
            g = jnp.einsum(
                f"{contract},{contract}lk->lk",
                ws.astype(table.dtype),
                table,
                precision=lax.Precision.HIGHEST,
            )
            g = lax.psum(g, WORKER_AXIS)
        return blocks_lib.blocks_to_tree(g, spec)

    return local


def _fused_layer_block_local_body(
    model, spec, contract: str, *,
    use_pallas: bool = False, interpret: bool = False,
) -> GradFn:
    """Fused variant of :func:`_layer_block_local_body`: the per-partition
    grad TABLE is never materialized.

    The treewise body packs every slot's gradient pytree into a
    zero-padded ``[M, L, width]`` block table (one fp copy of the whole
    gradient per slot, plus padding lanes) and einsum-decodes it. This
    body contracts each leaf's ``[M, D_leaf]`` slot view directly through
    :func:`ops.kernels.fused_block_decode` — same scalars, same reduction
    order, zero padding bytes streamed. Bitwise-identity notes:

      - the faithful "ws" contract's einsum lowers with contracting dims
        ``(s, w)`` — the flattened slot axis is S-MAJOR. Both the weights
        and each leaf are flattened in that order here (``ws.T``,
        ``moveaxis(leaf, 1, 0)``); a plain w-major ravel drifts in the
        last ulp (measured, ISSUE 19);
      - leaves are cast to the table dtype first (``jnp.concatenate``
        promotion in tree_to_blocks), so mixed-dtype pytrees decode in
        the same precision either way;
      - the per-leaf psum moves exactly the values the table psum moved,
        minus the padding lanes.
    ``use_pallas``/``interpret`` select the Mosaic kernel / its interpret
    mode inside fused_block_decode; the default lowers through one XLA
    dot_general per leaf (the fast CPU form — all three are bitwise-equal
    at precision=HIGHEST, tests/test_deep_coding.py)."""
    from erasurehead_tpu.ops import kernels as kernels_lib

    def local(params, Xs, ys, ws):
        per = lambda X, y: model.grad_sum(params, X, y)
        for _ in range(len(contract)):
            per = jax.vmap(per)
        with annotate("eh_step/partial_grads"):
            grads = per(Xs, ys)  # leaves [*contract axes, *leaf shape]
        with annotate("eh_step/decode"):
            leaves = jax.tree_util.tree_leaves(grads)
            tdtype = jnp.result_type(*leaves)
            if contract == "ws":
                wf = jnp.transpose(ws).reshape(-1)
            else:
                wf = ws.reshape(-1)
            M = wf.shape[0]

            def decode_leaf(leaf):
                leaf = leaf.astype(tdtype)
                if contract == "ws":
                    leaf = jnp.moveaxis(leaf, 1, 0)
                out_shape = leaf.shape[len(contract):]
                g2 = leaf.reshape(M, -1)
                out = kernels_lib.fused_block_decode(
                    wf, g2, use_pallas=use_pallas, interpret=interpret
                )
                return out.reshape(out_shape)

            g = jax.tree.map(decode_leaf, grads)
            g = lax.psum(g, WORKER_AXIS)
        return g

    return local


def make_layer_block_grad_fn(
    model, mesh: Mesh, spec, *, faithful: bool,
    fused: bool = False, use_pallas: bool = False, interpret: bool = False,
) -> GradFn:
    """Per-layer (blockwise) decoded gradient: drop-in for
    make_faithful_grad_fn / make_deduped_grad_fn on any model whose
    gradient is a pytree (the deep-model families). The ring transport
    composes via make_ring_faithful_grad_fn(local_body=...) exactly as
    the flat/margin-flat lowerings do. ``fused`` swaps the treewise
    pack-then-einsum body for the fused per-leaf contraction
    (:func:`_fused_layer_block_local_body`; resolve_block_decode owns the
    "auto" choice); the two are bitwise-identical, so the swap is a pure
    lowering fork — keyed into lowering_signature."""
    contract = "ws" if faithful else "p"
    body = (
        _fused_layer_block_local_body(
            model, spec, contract,
            use_pallas=use_pallas, interpret=interpret,
        )
        if fused
        else _layer_block_local_body(model, spec, contract)
    )
    return shard_map(
        _dq(body),
        mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS), P(WORKER_AXIS)),
        out_specs=P(),
        # the pallas flavor's out_shape carries no varying-across-mesh
        # info (same caveat as make_fused_grad_fn)
        check_vma=False if (fused and use_pallas) else _vma_check(model),
    )


def make_deduped_grad_fn(model, mesh: Mesh) -> GradFn:
    """Each partition gradient is computed exactly once, then combined with
    folded decode weights (CodingLayout.fold_slot_weights).

    No reference counterpart (the dedup is this framework's optimization);
    produces bit-comparable gradients to the faithful mode — tests pin the
    two together.

    Args of the returned fn:
      params: replicated pytree.
      Xp, yp: partition-major stacks [Pn, rows, F] / [Pn, rows], sharded.
      part_weights: [Pn] folded per-partition weights.
    """

    return shard_map(
        _dq(_deduped_local_body(model, mesh)),
        mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS), P(WORKER_AXIS)),
        out_specs=P(),
        check_vma=_vma_check(model),
    )


# ---------------------------------------------------------------------------
# trajectory-cohort batched dispatch: ONE stream of the device data stack
# serves B trajectories (scheme x seed x lr/alpha variants) at once


def _batched_local_body(local_body: GradFn) -> GradFn:
    """[B]-batched (params, weights) wrapper of a per-device local grad
    body: vmap over the leading trajectory axis of params and weights
    while the device's data shard enters UNBATCHED (in_axes None) — one
    HBM pass of X feeds every trajectory, and the per-slot margin matvecs
    become batched matmuls the MXU can tile. Falls out of the same local
    bodies the sequential trainers use, so the math can never drift."""

    def local(params_B, Xs, ys, ws_B):
        return jax.vmap(lambda p, w: local_body(p, Xs, ys, w))(
            params_B, ws_B
        )

    return local


def supports_cohort_matmul(model, X) -> bool:
    """The dedicated cohort body needs a closed-form GLM on a DENSE stack
    (the same support surface as the hybrid margin-flat lowering): the
    whole cohort's margins then lower as ONE [M*R, F] x [F, B] matmul."""
    return supports_margin_flat(model, X)


def _cohort_matmul_local_body(model) -> GradFn:
    """Dense closed-form GLM cohort body: the arithmetic-intensity lever.

    The sequential step's margin is a matVEC (X streams from HBM per
    trajectory); here the B trajectories' parameter vectors stack into a
    [F, B] operand so the margin lowers as one flat [M*R, F] x [F, B]
    matMUL and the transpose as [B, N] x [N, F] — B x the FLOPs per byte
    of X streamed, which is exactly what the bandwidth-bound roofline
    rewards (BASELINE.md "Arithmetic intensity"). Same math as B
    sequential steps; only the reduction order differs (tests pin
    allclose). dtype rules mirror _hybrid_margin_flat_grad / features:
    bf16 X streams as stored, the small operand casts down, the MXU
    accumulates f32."""
    from erasurehead_tpu.ops import features as features_lib

    def local(params_B, Xs, ys, ws_B):
        B = ws_B.shape[0]
        R = ys.shape[-1]
        F = Xs.shape[-1]
        M = int(np.prod(ys.shape[:-1]))
        N = M * R
        X2 = Xs.reshape(N, F)
        yf = ys.reshape(N)
        with annotate("eh_step/partial_grads"):
            if X2.dtype == jnp.bfloat16 and params_B.dtype != X2.dtype:
                margins = jnp.einsum(
                    "nf,bf->nb", X2, params_B.astype(X2.dtype),
                    preferred_element_type=jnp.float32,
                )
            else:
                margins = jnp.einsum(
                    "nf,bf->nb", X2, params_B,
                    precision=features_lib.get_default_precision(),
                )
            r = jax.vmap(model.margin_residual, in_axes=(1, None), out_axes=1)(
                margins, yf
            )  # [N, B]
            w_rows = jnp.broadcast_to(
                ws_B.reshape(B, M)[:, :, None], (B, M, R)
            ).reshape(B, N)
            wr = w_rows.astype(r.dtype) * jnp.swapaxes(r, 0, 1)  # [B, N]
            if X2.dtype == jnp.bfloat16 and wr.dtype != X2.dtype:
                g = -jnp.einsum(
                    "bn,nf->bf", wr.astype(X2.dtype), X2,
                    preferred_element_type=jnp.float32,
                )
            else:
                g = -jnp.einsum(
                    "bn,nf->bf", wr, X2,
                    precision=features_lib.get_default_precision(),
                )
        with annotate("eh_step/decode"):
            return lax.psum(g, WORKER_AXIS)

    return local


def make_cohort_grad_fn(
    model, mesh: Mesh, *, faithful: bool, ring_plan=None,
    local_body: GradFn = None, ring_pipeline: bool = False,
) -> GradFn:
    """Trajectory-cohort decoded gradients: one shard_map step whose
    params/weights lead with a [B] trajectory axis while the data stack is
    shared — the whole cohort rides ONE HBM stream of X per round.

    Args of the returned fn:
      params_B: pytree, leaves lead with [B]; replicated.
      X, y: the mode's stacks (partition-major for deduped and ring
        faithful, worker-major for materialized faithful), sharded on
        their leading axis.
      weights_B: [B, W, S] slot weights (faithful) or [B, Pn] folded
        per-partition weights (deduped), sharded on dim 1.
    Returns the decoded gradient pytree with leaves [B, ...], replicated.

    ``local_body`` must already be batched (``_cohort_matmul_local_body``
    or ``_batched_local_body(...)``); None picks the vmapped default body
    of the compute mode. ``ring_plan`` composes the ring transport exactly
    as make_ring_faithful_grad_fn does — the reconstructed worker buffer
    is shared across the cohort too, with ``ring_pipeline`` picking the
    double-buffered transport schedule. Compressed stacks dequantize once
    per step for the whole cohort (_dq wraps the batched body).
    """
    if local_body is None:
        local_body = _batched_local_body(
            _faithful_local_body(model, mesh)
            if faithful
            else _deduped_local_body(model, mesh)
        )
    local_body = _dq(local_body)
    if faithful and ring_plan is not None:
        inner = local_body

        def body(params_B, Xp, yp, ws_B):
            Xw, yw = _ring_fill(ring_plan, Xp, yp, pipeline=ring_pipeline)
            return inner(params_B, Xw, yw, ws_B)

    else:
        body = local_body
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS), P(None, WORKER_AXIS)),
        out_specs=P(),
        check_vma=_vma_check(model),
    )


# Whether flat_grad="auto" resolves to the flat lowering for DENSE and
# PaddedRows stacks. DECIDED False by the end-to-end race (v5e, round 3,
# tools/measurements.jsonl dense_f32_flat): the flat dense step measured
# 229 steps/s vs the per-slot step's 462-530 — despite the margin-pass
# profile favoring the flat 2-D matmul in isolation (margin_matmul2d
# 1.587 ms vs 1.843, tools/profile_dense.py), flattening the whole
# gradient loses the batched per-slot tiling of the transpose pass and
# doubles the step time. Per-slot stays the dense default; the flat form
# remains forceable (flat_grad="on") and is the FieldOnehot default,
# where it is the measured 10x fix (see resolve_flat_grad).
FLAT_GRAD_DEFAULT = False


def supports_flat_grad(model, X) -> bool:
    """make_flat_grad_fn needs a closed-form GLM (margin_residual) on any
    Features stack (dense, PaddedRows, FieldOnehot, or a dense
    QuantizedStack — dequantized first by _dq); autodiff families take
    ONE jax.grad per device instead (see _grads_via_loss)."""
    from erasurehead_tpu.ops import features as features_lib

    return hasattr(model, "margin_residual") and not _grads_via_loss(
        model
    ) and isinstance(
        X,
        (
            jax.Array,
            features_lib.PaddedRows,
            features_lib.FieldOnehot,
            features_lib.QuantizedStack,
        ),
    )


def resolve_flat_grad(flat_grad: str, model, X) -> bool:
    """Should this run use make_flat_grad_fn? ("on" validity is the
    caller's concern — this resolves the choice, it does not raise.)

    "auto" resolution is measurement-pinned per stack kind:
      - FieldOnehot: FLAT. The per-slot vmap materializes a
        [n_slots, pair-table] batch of scatter accumulators and measured
        catastrophically slow end-to-end on v5e (0.896 steps/s faithful
        covtype — ~10x under what its own one-accumulator profile
        candidates predict, tools/measurements.jsonl round 3); the flat
        lowering IS the one-accumulator form.
      - dense / PaddedRows: PER-SLOT. The dense end-to-end race measured
        the flat step at half the per-slot rate (229 vs 462-530 steps/s,
        dense_f32_flat, v5e round 3) — see FLAT_GRAD_DEFAULT.
    """
    if not supports_flat_grad(model, X):
        return False
    if flat_grad == "on":
        return True
    if flat_grad == "off":
        return False
    from erasurehead_tpu.ops import features as features_lib

    if isinstance(X, features_lib.FieldOnehot):
        return True
    return FLAT_GRAD_DEFAULT


def make_flat_grad_fn(model, mesh: Mesh) -> GradFn:
    """Closed-form GLM decoded gradient with the slot axes flattened away.

    Drop-in for make_faithful_grad_fn (worker-major [Wl, S, rows, ...])
    and make_deduped_grad_fn (partition-major [Pl, rows, ...]): instead of
    vmapping grad_sum per slot, the whole local stack becomes ONE flat
    Features operand (features.flatten_rows) and the per-slot decode
    weights fold into a per-row scale of the residual before the single
    transpose matvec:

        sum_s w_s * (-X_s^T r_s)  ==  -Xf^T (w_row * r)     (exact)

    Why it's faster than the per-slot vmap on TPU (measured, round 3):
      - dense: the margin is a single flat 2-D matmul — 1.587 ms vs the
        batched per-tile contraction's 1.843 ms at the canonical
        [90, 4400, 128], AT the raw-stream floor (profile_dense);
      - sparse: the gradient scatter-add targets ONE accumulator (per
        pair table / per column space) instead of materializing a
        [n_slots, table]-shaped batch of per-slot accumulators — the
        transient that made the vmapped FieldOnehot path ~10x slower
        end-to-end than its own profiled candidates.

    Same math and FLOPs as the per-slot form; only the reduction order
    differs (tests pin the two to allclose, not bitwise).
    """

    return shard_map(
        _dq(_flat_local_body(model)),
        mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS), P(WORKER_AXIS)),
        out_specs=P(),
    )


def _flat_local_body(model) -> GradFn:
    """Per-device body of make_flat_grad_fn; also the ring transport's
    local grad when flat_grad resolves on (make_ring_faithful_grad_fn)."""

    def local(params, Xs, ys, ws):
        from erasurehead_tpu.ops import features as features_lib

        with annotate("eh_step/partial_grads"):
            M = int(np.prod(ys.shape[:-1]))
            R = ys.shape[-1]
            Xf = features_lib.flatten_rows(Xs)
            yf = ys.reshape(M * R)
            # [M] slot weights -> [M*R] row weights: the decode CONTRACTION
            # is folded into the residual here, so this region carries both
            # the partial-gradient compute and the weighted combine
            wf = jnp.broadcast_to(
                ws.reshape(M)[:, None], (M, R)
            ).reshape(M * R)
            p = features_lib.matvec(Xf, params)  # bf16 + lanes/cols aware
            r = model.margin_residual(p, yf)
            g = -features_lib.rmatvec(Xf, wf.astype(r.dtype) * r)
        with annotate("eh_step/decode"):
            return lax.psum(g, WORKER_AXIS)

    return local


def make_fused_grad_fn(kind: str, mesh: Mesh, *, interpret: bool = False) -> GradFn:
    """Single-pass pallas decoded gradient (ops/kernels.py) under shard_map.

    Drop-in for make_faithful_grad_fn / make_deduped_grad_fn on dense GLM
    stacks: accepts either the worker-major [Wl, S, rows, F] or the
    partition-major [Pl, rows, F] shape (leading dims are flattened into
    kernel slots), computes margin -> residual -> weighted
    transpose-accumulate in ONE streaming read of X instead of XLA's two,
    then psums over the worker axis. ``interpret=True`` runs the kernel in
    pallas interpret mode for CPU tests.
    """
    from erasurehead_tpu.ops import kernels

    def local(params, Xs, ys, ws):
        lead = Xs.shape[:-2]
        M = int(np.prod(lead))
        Xf = Xs.reshape((M,) + Xs.shape[-2:])
        yf = ys.reshape(M, -1)
        wf = ws.reshape(M)
        with annotate("eh_step/partial_grads"):
            g = kernels.fused_glm_grad(
                params, Xf, yf, wf, kind, interpret=interpret
            )
        with annotate("eh_step/decode"):
            return lax.psum(g, WORKER_AXIS)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS), P(WORKER_AXIS)),
        out_specs=P(),
        # pallas_call's out_shape carries no varying-across-mesh info, so
        # jax 0.9's vma checker cannot validate this body
        check_vma=False,
    )


def lowering_signature(cfg, model, X) -> tuple:
    """The RESOLVED gradient-lowering choice for (cfg, model, stack) — the
    part of the sweep-engine executable cache key (train/cache.py) that
    cfg alone cannot determine: resolve_flat_grad / resolve_margin_flat
    depend on the model class and the materialized stack kind, and their
    defaults (FLAT_GRAD_DEFAULT / MARGIN_FLAT_DEFAULT) are
    measurement-pinned module state that future races may flip. Keying on
    the resolution rather than the knob strings keeps a cached executable
    from surviving a default flip — and, since ISSUE 19, from surviving a
    tune decision-cache update (the resolvers consult the cache, so the
    resolved tuple moves when a race verdict lands)."""
    return (
        bool(resolve_flat_grad(cfg.flat_grad, model, X)),
        bool(resolve_margin_flat(cfg.margin_flat, model, X)),
        bool(resolve_layer_coding(cfg.layer_coding, model, X)),
        bool(
            resolve_block_decode(
                getattr(cfg, "block_decode", "auto"), model, X
            )
        ),
        type(X).__name__,
    )


def staleness_slot_params(params, stale_params, pipeline_depth: int):
    """The params slot the weighted-sum/refit decode contracts against.

    Synchronous runs (``pipeline_depth=0``) read the scan carry's live
    params; pipelined runs (tau=1) read the SECOND carry slot — the params
    round r's workers were actually dispatched with (round r-1's entering
    iterate, train/trainer.py's restructured carry). A static Python
    branch, resolved at trace time: the tau=0 program is byte-identical to
    the pre-pipeline lowering (the carry never grows a slot), which is
    what keeps ``pipeline_depth=0`` bitwise today's trainer."""
    return stale_params if pipeline_depth else params


def expand_slot_weights(
    message_weights: jnp.ndarray,
    coeffs: jnp.ndarray,
    slot_is_coded: jnp.ndarray,
) -> jnp.ndarray:
    """[R?, W] per-message decode weights -> [R?, W, S] per-slot weights.

    Coded slots are scaled by the message's decode weight; separate slots
    (partial schemes' uncoded first parts) always contribute with weight 1
    (src/partial_coded.py:187-190: every first part is added unscaled).

    This is the single home of that rule: both compute modes (and the host
    float64 control plane) derive their weights from it, so it accepts numpy
    inputs without forcing a float32 round-trip through jnp.
    """
    xp = np if isinstance(message_weights, np.ndarray) else jnp
    a = message_weights[..., :, None]
    return xp.where(slot_is_coded, a * coeffs, coeffs)
