"""Command-line entry point.

Two invocation forms:

1. **Named flags** (the native form)::

       python -m erasurehead_tpu.cli --scheme approx --workers 30 \\
           --stragglers 3 --num-collect 15 --rounds 100 --dataset artificial \\
           --rows 4096 --cols 100 --update-rule AGD --add-delay

2. **Legacy positional** — the reference's 13-argument calling convention
   (main.py:20-27), accepted verbatim so reference launch scripts translate
   mechanically (mpirun disappears; n_procs keeps its master+workers
   meaning)::

       python -m erasurehead_tpu.cli n_procs n_rows n_cols input_dir is_real \\
           dataset is_coded n_stragglers partitions coded_ver num_collect \\
           add_delay update_rule

   Dispatch parity (main.py:62-92): is_coded=0 -> naive; coded_ver 0 ->
   cyclic MDS (partial if partitions>0), 1 -> FRC (partial if partitions>0),
   2 -> avoidstragg, 3 -> AGC; dataset "kc_house_data" selects the linear
   model (main.py:75-78,83-92).

Run flow: load or generate the dataset, train on the device mesh, replay the
eval, write the five artifacts into ``<input_dir>/.../results/`` (the
reference's layout, src/naive.py:200-208). With ``--telemetry on`` (or
``auto`` + ``--output-dir``) an ``events.jsonl`` run log lands beside them.

A third form renders that log::

       erasurehead-tpu report <events.jsonl> [more.jsonl ...]

A fourth runs the comparison-suite sweep (train/experiments.py) behind the
same console entry, with the resilient-sweep flags::

       erasurehead-tpu sweep --rounds 30 --sweep-journal DIR --resume-sweep

A fifth runs the multi-tenant sweep-as-a-service daemon
(erasurehead_tpu/serve/): concurrent clients' compatible requests bin-pack
into shared cohort dispatches under an HBM admission budget — weighted-
fair across tenants, with an HTTP/1.1 JSONL front (per-tenant bearer
tokens, chunked result streaming, 429 + Retry-After backpressure) and
crash-safe warm restarts (intake WAL + JAX's on-disk compilation cache)::

       erasurehead-tpu serve --socket /tmp/eh.sock --budget 2g \\
           --http 0.0.0.0:8080 --auth-tokens tokens.json \\
           --journal-dir /var/lib/eh-serve --cache-dir /var/lib/eh-xla \\
           --max-pending 256 --request-timeout 600 \\
           --events serve_events.jsonl

A sixth runs the AST invariant analyzer (erasurehead_tpu/analysis/) over
the tree — the trace/cache/telemetry contract checks tier-1 gates on::

       erasurehead-tpu lint [--strict] [paths]

A seventh runs the what-if engine (erasurehead_tpu/whatif/): Monte-Carlo
policy search over the (scheme, W, s, collect, deadline, regime) grid as
batched cohort dispatches, reduced to an expected-time-to-target surface
artifact whose rows seed the adapt/ bandit's cold start and the serve
daemon's admission-time ETA quotes::

       erasurehead-tpu whatif --policies naive,cyccoded,approx \\
           --workers 8 --stragglers 1,3 --regimes exp:0.1,exp:2.0 \\
           --seeds 16 --out surfaces/small --crossover approx,cyccoded

An eighth runs the measured autotuning plane (erasurehead_tpu/tune/):
races auto-gated lowering pairs (block_decode, layer_coding, glm_fused,
ring_pipeline, stack_mode) at a run shape and persists the verdicts to
the JSON decision cache every ``auto`` knob resolves through — the
explicit moment measurement happens, so training and serving never
re-race::

       erasurehead-tpu tune --race block_decode --race glm_fused \\
           --model deepmlp --workers 8 --rows 4096 --cols 256
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

from erasurehead_tpu.data import io as data_io
from erasurehead_tpu.data.synthetic import Dataset, generate_gmm
from erasurehead_tpu.parallel import failures
from erasurehead_tpu.parallel.backend import initialize_distributed
from erasurehead_tpu.train import artifacts, evaluate, trainer
from erasurehead_tpu.utils.config import ModelKind, RunConfig, Scheme


def _legacy_to_config(argv: list[str]) -> RunConfig:
    """Map the reference's 13 positional args onto a RunConfig."""
    (
        n_procs, n_rows, n_cols, input_dir, is_real, dataset, is_coded,
        n_stragglers, partitions, coded_ver, num_collect, add_delay,
        update_rule,
    ) = argv
    n_procs, n_rows, n_cols = int(n_procs), int(n_rows), int(n_cols)
    is_real, is_coded = int(is_real), int(is_coded)
    n_stragglers, partitions, coded_ver = (
        int(n_stragglers), int(partitions), int(coded_ver),
    )
    num_collect, add_delay = int(num_collect), int(add_delay)

    if not is_coded:
        scheme = Scheme.NAIVE
    elif partitions:
        table = {1: Scheme.PARTIAL_FRC, 0: Scheme.PARTIAL_CYCLIC}
        if coded_ver not in table:
            raise SystemExit(
                f"coded_ver={coded_ver} invalid with partitions>0 "
                f"(0=partial coded, 1=partial replication; main.py:64-68)"
            )
        scheme = table[coded_ver]
    else:
        table = {
            0: Scheme.CYCLIC_MDS,
            1: Scheme.FRC,
            2: Scheme.AVOID_STRAGGLERS,
            3: Scheme.APPROX,
        }
        if coded_ver not in table:
            raise SystemExit(
                f"coded_ver={coded_ver} invalid (0=cyclic MDS, 1=FRC, "
                f"2=avoidstragg, 3=AGC; main.py:70-87)"
            )
        scheme = table[coded_ver]
    model = (
        ModelKind.LINEAR if dataset == "kc_house_data" else ModelKind.LOGISTIC
    )
    return RunConfig(
        scheme=scheme,
        model=model,
        n_workers=n_procs - 1,  # reference: rank 0 is the master
        n_stragglers=n_stragglers,
        num_collect=num_collect if num_collect > 0 else None,
        add_delay=bool(add_delay),
        update_rule=update_rule,
        dataset=dataset if is_real else "artificial",
        n_rows=n_rows,
        n_cols=n_cols,
        input_dir=input_dir,
        is_real_data=bool(is_real),
        partitions_per_worker=partitions,
    )


def _flags_parser() -> argparse.ArgumentParser:
    from erasurehead_tpu import schemes as schemes_lib

    p = argparse.ArgumentParser(
        prog="erasurehead-tpu",
        description="Straggler-tolerant coded gradient descent on TPU",
    )
    # --scheme choices come from the registry (erasurehead_tpu/schemes/),
    # so entry-point-registered third-party schemes appear here without
    # touching this file
    p.add_argument("--scheme", default="naive", choices=schemes_lib.names())
    p.add_argument("--model", default=None, choices=[m.value for m in ModelKind])
    p.add_argument("--workers", type=int, default=8)
    p.add_argument("--stragglers", type=int, default=1)
    p.add_argument("--num-collect", type=int, default=None)
    p.add_argument("--deadline", type=float, default=None,
                   help="per-round collection deadline in simulated "
                        "seconds (scheme=deadline)")
    p.add_argument("--decode", default="fixed", choices=["fixed", "optimal"],
                   help="decode-weight policy: 'optimal' refits the "
                        "collection weights per round to the ACTUAL "
                        "arrival set (least-squares over the layout's "
                        "effective coding matrix, arXiv:2006.09638) — "
                        "error <= the scheme's fixed weights round for "
                        "round (obs/decode.py proves it); 'fixed' keeps "
                        "the reference behavior")
    p.add_argument("--adapt", default="off", choices=["off", "on"],
                   help="online straggler-adaptive collection (adapt/): "
                        "a seeded bandit re-chooses the (scheme, collect, "
                        "deadline) policy at every --adapt-chunk boundary "
                        "from the run's own decode-error and arrival "
                        "telemetry, switching when the straggler regime "
                        "shifts; decisions are journaled as typed `adapt` "
                        "events")
    p.add_argument("--adapt-chunk", type=int, default=10,
                   help="rounds per adaptive decision window")
    p.add_argument("--elastic", default="off", choices=["off", "on"],
                   help="online elastic membership (elastic/): train in "
                        "chunks and, between chunks, detect dead workers "
                        "from the run's own telemetry (the -1 never-"
                        "arrived sentinel persisting --death-rounds "
                        "rounds, or a --death-timeout trip), re-layout "
                        "onto the survivors via the scheme registry's "
                        "layout builders with params+momentum carried "
                        "over, and scale back UP when a worker rejoins "
                        "(chaos worker_revive). --kill-workers scripts "
                        "the ground-truth world; the controller only "
                        "ever sees telemetry. Decisions land as typed "
                        "`membership` events")
    p.add_argument("--elastic-chunk", type=int, default=10,
                   help="rounds per elastic membership chunk (the "
                        "checkpoint/re-layout granularity)")
    p.add_argument("--death-rounds", type=int, default=3,
                   help="consecutive never-arrived rounds that declare a "
                        "worker dead (elastic mode)")
    p.add_argument("--adapt-arms", default=None, metavar="SPEC",
                   help="comma-separated arms 'scheme[:cN][:dSECS]', e.g. "
                        "'naive,approx:c4,deadline:d1.5'; default: the "
                        "run's own policy plus the uncoded-layout "
                        "alternatives (adapt.default_arms)")
    p.add_argument("--adapt-priors", default=None, metavar="DIR",
                   help="seed the adapt bandit's cold start from a "
                        "what-if surface artifact (`erasurehead-tpu "
                        "whatif --out DIR`): arm values start at the "
                        "surface's simulated expected reward instead of "
                        "zero, so warm-up only explores arms the surface "
                        "could not rank")
    p.add_argument("--rounds", type=int, default=100)
    p.add_argument("--dataset", default="artificial")
    p.add_argument("--rows", type=int, default=4096)
    p.add_argument("--cols", type=int, default=100)
    p.add_argument("--input-dir", default=None, help="reference-layout data dir")
    p.add_argument("--output-dir", default=None, help="artifact dir (default <input>/results)")
    p.add_argument("--update-rule", default="AGD", choices=["GD", "AGD", "ADAM"])
    p.add_argument("--lr", type=float, default=None, help="constant lr override")
    p.add_argument("--alpha", type=float, default=None, help="l2 coefficient")
    p.add_argument("--add-delay", action="store_true")
    p.add_argument("--delay-mean", type=float, default=0.5)
    p.add_argument("--compute-time", type=float, default=0.0,
                   help="simulated per-round compute seconds per worker")
    p.add_argument("--worker-speed-spread", type=float, default=0.0,
                   help="uniform per-worker speed spread in [1-s,1+s]")
    p.add_argument("--partitions-per-worker", type=int, default=0)
    p.add_argument("--compute-mode", default="faithful", choices=["faithful", "deduped"])
    p.add_argument("--stack-mode", default="materialized",
                   choices=["materialized", "ring", "auto"],
                   help="faithful-mode stack transport: 'ring' keeps only "
                        "the partition-major stack and streams each "
                        "device's redundant slots from its ring neighbors "
                        "per step (bitwise-identical trajectories, (s+1)x "
                        "less device data); 'auto' switches to ring past a "
                        "footprint estimate")
    p.add_argument("--ring-pipeline", default="auto",
                   choices=["auto", "on", "off"],
                   help="ring-transport scheduling under stack-mode ring: "
                        "'on' double-buffers the hops (the ppermute for "
                        "hop t+1 is issued while hop t's block fills, so "
                        "ICI transfers overlap on-chip fills; same hops, "
                        "same bytes, bitwise-identical trajectories); "
                        "'off' keeps the sequential transport; 'auto' = "
                        "the measurement-pinned default (off pending the "
                        "dense_f32_ringpipe race)")
    p.add_argument("--stack-dtype", default="auto",
                   choices=["auto", "float32", "bfloat16", "int8"],
                   help="feature-stack STORAGE dtype: int8 quantizes the "
                        "partition-major stack at upload (per-partition "
                        "scale tables, dequantized inside the device grad "
                        "body) — ~4x fewer streamed bytes, LOSSY (the "
                        "fidelity cost is measured per scheme: bench.py "
                        "fidelity extra, decode-error columns); auto "
                        "follows --dtype")
    p.add_argument("--stack-residency", default="resident",
                   choices=["resident", "streamed", "auto"],
                   help="where the partition stack LIVES: 'streamed' "
                        "keeps it in an on-disk shard store (data/"
                        "store.py) and materializes only a window of "
                        "partitions per scan chunk, double-buffered by a "
                        "host prefetcher — data larger than HBM trains "
                        "on a fixed byte budget (ERASUREHEAD_STREAM_"
                        "WINDOW); a window covering the whole stack is "
                        "bitwise-identical to resident. 'auto' streams "
                        "exactly when the budget env is set")
    p.add_argument("--stream-window", type=int, default=None,
                   help="streamed residency: partitions per window "
                        "(default: sized so TWO windows fit the "
                        "ERASUREHEAD_STREAM_WINDOW byte budget; rounded "
                        "down to a divisor of the partition count)")
    p.add_argument("--donate", default="auto", choices=["auto", "on", "off"],
                   help="buffer donation for the training scan's carry "
                        "(params + optimizer state) and per-round weight "
                        "tables: frees the duplicate HBM copy per "
                        "dispatch; bitwise-identical math, cached data "
                        "stacks are never donated. auto = on")
    p.add_argument("--use-pallas", default="auto", choices=["auto", "on", "off"],
                   help="fused pallas gradient kernel (ops/kernels.py). "
                        "The shipped end-to-end races measured it VPU-"
                        "bound (XLA won all three on v5e), so auto "
                        "declines unless a cached `erasurehead-tpu tune "
                        "--race glm_fused` verdict at this run's shape "
                        "says pallas wins; 'on' forces it anyway, and "
                        "excludes the batched trajectory-cohort dispatch)")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="DATA dtype (params/updates stay float32)")
    p.add_argument("--arrival-mode", default="simulated",
                   choices=["simulated", "measured"],
                   help="measured: time each worker's real per-round "
                        "gradient compute and collect on those arrivals "
                        "(trainer.train_measured)")
    p.add_argument("--sparse-lanes", type=int, default=None,
                   help="sparse margin-gather lane width (power of two; "
                        "TPU scalar-gather workaround). Applies to "
                        "PaddedRows value gathers and FieldOnehot "
                        "pair-table gathers; the scatter stays scalar")
    p.add_argument("--sparse-format", default="padded",
                   choices=["padded", "fields", "auto"],
                   help="sparse stack representation: fields = FieldOnehot "
                        "fused pair-table lowering (one-hot data only)")
    p.add_argument("--fields-scatter", default="pairs",
                   choices=["pairs", "onehot"],
                   help="FieldOnehot gradient-scatter lowering: onehot = "
                        "per-field one-hot MXU matmuls instead of "
                        "pair-accumulator scatter-adds")
    p.add_argument("--fields-margin", default="tables",
                   choices=["tables", "onehot"],
                   help="FieldOnehot margin lowering: onehot = per-field "
                        "one-hot MXU matmuls instead of pair-table gathers")
    p.add_argument("--dense-margin-cols", type=int, default=None,
                   help="dense margin matvec lowering width [2,128]: "
                        "replicate beta behind a barrier so the margin "
                        "lowers as a tileable matmul (exact; column 0)")
    p.add_argument("--scan-unroll", type=int, default=1,
                   help="lax.scan unroll factor for the training scan: "
                        ">1 lets XLA fuse/overlap consecutive rounds "
                        "(identical math; a lowering knob)")
    p.add_argument("--pipeline-depth", type=int, default=0,
                   choices=[0, 1],
                   help="pipelined training with bounded staleness tau "
                        "(parallel/pipeline.py): 1 dispatches round t+1's "
                        "worker compute against round t-1's params while "
                        "round t's arrivals drain. Deterministic and "
                        "journal-replayable; refuses (typed "
                        "PipelineRefusal) exact-decode schemes, non-GD "
                        "rules and measured arrivals. 0 = synchronous "
                        "(bitwise today's trainer)")
    p.add_argument("--flat-grad", default="auto",
                   choices=["auto", "on", "off"],
                   help="flat-stack closed-form GLM gradient lowering "
                        "(parallel/step.make_flat_grad_fn): margin as one "
                        "2-D matmul, decode weights folded into the "
                        "residual")
    p.add_argument("--layer-coding", default="auto",
                   choices=["auto", "on", "off"],
                   help="per-layer (blockwise) gradient coding "
                        "(parallel/step.make_layer_block_grad_fn): each "
                        "layer's flattened gradient block decodes as its "
                        "own small einsum (DeepMLP layers / MoE expert "
                        "shards are individual coded blocks); bitwise-"
                        "identical decode, a pure lowering knob")
    p.add_argument("--block-decode", default="auto",
                   choices=["auto", "fused", "treewise"],
                   help="blockwise-decode lowering under --layer-coding: "
                        "'treewise' packs per-layer grad tables then "
                        "einsum-decodes; 'fused' contracts each gradient "
                        "leaf directly against the decode weights "
                        "(ops/kernels.fused_block_decode) with no "
                        "materialized per-partition table. Bitwise-"
                        "identical decode; auto resolves through the "
                        "tune decision cache (erasurehead-tpu tune) "
                        "then the hardcoded default. "
                        "ERASUREHEAD_BLOCK_DECODE overrides")
    p.add_argument("--deep-layers", type=int, default=0,
                   help="hidden-layer count for model='deepmlp' (0 = the "
                        "model default); the decode-error-vs-depth sweep "
                        "knob")
    p.add_argument("--arrival-trace", default=None, metavar="PATH",
                   help="replay a recorded [rounds, workers] arrival-time "
                        "trace (.npy/.npz/.csv/.txt; tiled over rounds) "
                        "instead of drawing i.i.d. exponential delays; "
                        "ERASUREHEAD_ARRIVAL_TRACE when unset. "
                        "--worker-speed-spread composes as a per-worker "
                        "multiplier on the trace rows")
    p.add_argument("--seq-shards", type=int, default=1,
                   help="sequence-parallel shards for the attention model: "
                        ">1 builds a 2-D (workers, seq) mesh and spans the "
                        "token axis over it")
    p.add_argument("--sp-form", default="ring", choices=["ring", "ulysses"],
                   help="SP form carrying the attention: ppermute ring or "
                        "all-to-all head sharding")
    p.add_argument("--tp-shards", type=int, default=1,
                   help="tensor-parallel shards for the MLP model: >1 "
                        "builds a 2-D (workers, model) mesh and splits the "
                        "hidden dimension over it")
    p.add_argument("--pp-shards", type=int, default=1,
                   help="pipeline stages for the deepmlp model: >1 builds "
                        "a 2-D (workers, pipe) mesh and streams GPipe "
                        "microbatches through the layer stages")
    p.add_argument("--ep-shards", type=int, default=1,
                   help="expert-parallel shards for the moe model: >1 "
                        "builds a 2-D (workers, expert) mesh and splits "
                        "the experts over it")
    p.add_argument("--sweep-cache", default="on", choices=["on", "off"],
                   help="sweep-engine executable/data caches "
                        "(train/cache.py): off forces every run to "
                        "recompile and re-upload (debugging; memory "
                        "pressure). ERASUREHEAD_SWEEP_CACHE=0 in the env "
                        "does the same")
    p.add_argument("--telemetry", default=None, choices=["on", "off", "auto"],
                   help="run-telemetry event log (obs/): writes "
                        "events.jsonl beside the artifacts — typed "
                        "run_start/compile/data_upload/rounds/decode/"
                        "run_end records, rendered by `erasurehead-tpu "
                        "report`. Default: ERASUREHEAD_TELEMETRY env, "
                        "else off; auto = on when --output-dir is given. "
                        "Observation-only: trajectories are bitwise "
                        "identical either way")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--checkpoint-dir", default=None,
                   help="save optimizer state here every --checkpoint-every "
                        "rounds (orbax)")
    p.add_argument("--checkpoint-every", type=int, default=None)
    p.add_argument("--resume", action="store_true",
                   help="restart from the latest checkpoint in "
                        "--checkpoint-dir; artifacts cover the resumed "
                        "window [start_round, rounds)")
    p.add_argument("--trace-dir", default=None,
                   help="capture a jax.profiler device trace here")
    p.add_argument("--kill-workers", default=None, metavar="W:R[,W:R...]",
                   help="fault injection: kill worker W permanently at "
                        "round R (e.g. 6:10,7:12)")
    p.add_argument("--on-death", default="error",
                   choices=["error", "failover", "elastic"],
                   help="error: raise where the reference would hang; "
                        "failover: degrade infeasible rounds' decode "
                        "(needs --death-timeout); elastic: re-shard onto "
                        "the survivors and continue (failures.train_elastic)")
    p.add_argument("--death-timeout", type=float, default=None,
                   help="simulated seconds before the master presumes a "
                        "worker dead (failover mode)")
    p.add_argument("--quiet", action="store_true")
    return p


def _flags_to_config(ns: argparse.Namespace) -> RunConfig:
    model = ns.model
    if model is None:
        model = (
            ModelKind.LINEAR
            if ns.dataset == "kc_house_data"
            else ModelKind.LOGISTIC
        )
    return RunConfig(
        scheme=ns.scheme,
        model=model,
        n_workers=ns.workers,
        n_stragglers=ns.stragglers,
        num_collect=ns.num_collect,
        deadline=ns.deadline,
        decode=ns.decode,
        rounds=ns.rounds,
        add_delay=ns.add_delay,
        delay_mean=ns.delay_mean,
        compute_time=ns.compute_time,
        worker_speed_spread=ns.worker_speed_spread,
        update_rule=ns.update_rule,
        alpha=ns.alpha,
        lr_schedule=ns.lr,
        dataset=ns.dataset,
        n_rows=ns.rows,
        n_cols=ns.cols,
        input_dir=ns.input_dir,
        is_real_data=ns.input_dir is not None and ns.dataset != "artificial",
        partitions_per_worker=ns.partitions_per_worker,
        compute_mode=ns.compute_mode,
        stack_mode=ns.stack_mode,
        ring_pipeline=ns.ring_pipeline,
        stack_dtype=ns.stack_dtype,
        stack_residency=ns.stack_residency,
        stream_window=ns.stream_window,
        donate=ns.donate,
        use_pallas=ns.use_pallas,
        dtype=ns.dtype,
        arrival_mode=ns.arrival_mode,
        sparse_lanes=ns.sparse_lanes,
        dense_margin_cols=ns.dense_margin_cols,
        flat_grad=ns.flat_grad,
        layer_coding=ns.layer_coding,
        block_decode=ns.block_decode,
        deep_layers=ns.deep_layers,
        arrival_trace=ns.arrival_trace,
        scan_unroll=ns.scan_unroll,
        pipeline_depth=ns.pipeline_depth,
        sparse_format=ns.sparse_format,
        fields_scatter=ns.fields_scatter,
        fields_margin=ns.fields_margin,
        seq_shards=ns.seq_shards,
        sp_form=ns.sp_form,
        tp_shards=ns.tp_shards,
        pp_shards=ns.pp_shards,
        ep_shards=ns.ep_shards,
        seed=ns.seed,
    )


def dataset_dir(cfg: RunConfig) -> str | None:
    """The reference's on-disk dataset directory for this config
    (path synthesis: main.py:59-60, generate_data.py:59-62)."""
    if not cfg.input_dir:
        return None
    sub = (
        cfg.dataset
        if cfg.is_real_data
        else f"artificial-data/{cfg.n_rows}x{cfg.n_cols}"
    )
    leaf = (
        str(cfg.n_workers)
        if not cfg.partitions_per_worker
        else f"partial/{(cfg.partitions_per_worker - cfg.n_stragglers) * cfg.n_workers}"
    )
    return os.path.join(cfg.input_dir, sub, leaf)


def load_dataset(cfg: RunConfig) -> Dataset:
    """Reference-layout directory if present, else in-memory synthetic.

    A real-data config whose directory is missing is an error — silently
    training on synthetic noise and labeling the artifacts as the real
    dataset would be worse than failing."""
    n_partitions = (
        cfg.n_workers
        if not cfg.partitions_per_worker
        else (cfg.partitions_per_worker - cfg.n_stragglers) * cfg.n_workers
    )
    path = dataset_dir(cfg)
    if data_io.has_reference_layout(path):
        return data_io.read_reference_layout(path, n_partitions)
    if cfg.is_real_data:
        raise FileNotFoundError(
            f"real dataset {cfg.dataset!r} not found at {path!r}; prepare it "
            f"with erasurehead_tpu.data.real / data_io.write_reference_layout"
        )
    if cfg.model == ModelKind.LINEAR:
        from erasurehead_tpu.data.synthetic import generate_linear

        return generate_linear(cfg.n_rows, cfg.n_cols, n_partitions, cfg.seed)
    return generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions, cfg.seed)


def _validate_checkpoint_flags(parser, ns) -> None:
    """Interdependent checkpoint flags: fail fast with a proper CLI
    diagnostic (exit code 2), before backend init / dataset load."""
    if ns.resume and not ns.checkpoint_dir:
        parser.error("--resume requires --checkpoint-dir")
    if ns.checkpoint_every is not None and ns.checkpoint_every < 1:
        parser.error("--checkpoint-every must be >= 1")
    if ns.checkpoint_dir and not ns.resume and ns.checkpoint_every is None:
        parser.error(
            "--checkpoint-dir without --checkpoint-every never saves; "
            "pass --checkpoint-every N"
        )
    if ns.checkpoint_every is not None and not ns.checkpoint_dir:
        parser.error("--checkpoint-every requires --checkpoint-dir")
    if (ns.checkpoint_dir or ns.resume) and ns.arrival_mode == "measured":
        parser.error(
            "checkpoint/resume is implemented for the scan trainer only; "
            "unset --arrival-mode measured"
        )
    # fault-injection flags: --on-death/--death-timeout only mean anything
    # with --kill-workers; silently ignoring them would let a typo'd run
    # masquerade as a recovery experiment
    if ns.on_death != "error" and not ns.kill_workers:
        parser.error("--on-death requires --kill-workers")
    if ns.death_timeout is not None and ns.on_death != "failover" \
            and ns.elastic != "on":
        parser.error(
            "--death-timeout only applies to --on-death failover or "
            "--elastic on"
        )
    if ns.kill_workers and ns.on_death == "failover" and ns.death_timeout is None:
        parser.error("--on-death failover requires --death-timeout")
    if ns.kill_workers and (ns.checkpoint_dir or ns.resume):
        parser.error("--kill-workers does not compose with checkpointing")
    if ns.kill_workers and ns.arrival_mode == "measured":
        parser.error("--kill-workers needs the simulated-arrival trainer")
    # elastic membership: the driver owns the chunking and the failure
    # handling, so the static death paths don't compose with it
    if ns.elastic == "on":
        if ns.arrival_mode == "measured":
            parser.error("--elastic needs the simulated-arrival trainer")
        if ns.checkpoint_dir or ns.resume:
            parser.error(
                "--elastic manages its own chunk-boundary checkpoints; "
                "drop --checkpoint-dir/--resume (elastic resume is the "
                "driver API's checkpoint_dir/resume)"
            )
        if ns.adapt == "on":
            parser.error(
                "--elastic composes the adapt bandit internally (per-"
                "epoch re-seeded arms); drop --adapt"
            )
        if ns.on_death != "error":
            parser.error(
                "--elastic IS the death handling; drop --on-death"
            )
    if ns.elastic_chunk < 1:
        parser.error("--elastic-chunk must be >= 1")
    if ns.death_rounds < 1:
        parser.error("--death-rounds must be >= 1")
    # adaptive collection: the driver owns the chunking, so the static
    # checkpoint/fault paths don't compose with it
    if ns.adapt == "on":
        if ns.arrival_mode == "measured":
            parser.error("--adapt needs the simulated-arrival trainer")
        if ns.checkpoint_dir or ns.resume:
            parser.error("--adapt does not compose with checkpointing")
        if ns.kill_workers:
            parser.error("--adapt does not compose with --kill-workers")
    if ns.adapt_chunk < 1:
        parser.error("--adapt-chunk must be >= 1")
    if ns.adapt_arms is not None and ns.adapt != "on":
        parser.error("--adapt-arms requires --adapt on")
    if ns.adapt_priors is not None and ns.adapt != "on":
        parser.error("--adapt-priors requires --adapt on")


def _parse_deaths(spec: str) -> dict[int, int]:
    """'6:10,7:12' -> {6: 10, 7: 12} (worker: death round)."""
    out: dict[int, int] = {}
    for part in spec.split(","):
        w, _, r = part.partition(":")
        try:
            wi, ri = int(w), int(r)
        except ValueError:
            raise ValueError(
                f"bad --kill-workers entry {part!r}; want worker:round"
            ) from None
        if wi in out:
            raise ValueError(
                f"--kill-workers lists worker {wi} twice "
                f"({out[wi]} and {ri}) — likely a typo"
            )
        out[wi] = ri
    return out


def _parse_arms(spec: str):
    """'naive,approx:c4,deadline:d1.5' -> [Arm, ...] (cN = num_collect,
    dSECS = deadline; order-free within one arm)."""
    from erasurehead_tpu.adapt import Arm

    arms = []
    for part in spec.split(","):
        fields = part.strip().split(":")
        if not fields or not fields[0]:
            raise ValueError(f"bad --adapt-arms entry {part!r}")
        scheme, num_collect, deadline = fields[0], None, None
        for f in fields[1:]:
            try:
                if f.startswith("c"):
                    num_collect = int(f[1:])
                elif f.startswith("d"):
                    deadline = float(f[1:])
                else:
                    raise ValueError
            except ValueError:
                raise ValueError(
                    f"bad --adapt-arms field {f!r} in {part!r}; want cN "
                    "(collect count) or dSECS (deadline)"
                ) from None
        arms.append(Arm(scheme, num_collect=num_collect, deadline=deadline))
    return arms


def run(
    cfg: RunConfig,
    output_dir: str | None = None,
    quiet: bool = False,
    trace_dir: str | None = None,
    checkpoint_dir: str | None = None,
    checkpoint_every: int | None = None,
    resume: bool = False,
    kill_workers: str | None = None,
    on_death: str = "error",
    death_timeout: float | None = None,
    telemetry: str | None = None,
    adapt: str = "off",
    adapt_chunk: int = 10,
    adapt_arms: str | None = None,
    adapt_priors: str | None = None,
    elastic: str = "off",
    elastic_chunk: int = 10,
    death_rounds: int = 3,
):
    # argument-only checks: fail before backend init / dataset load
    if (checkpoint_dir or resume) and cfg.arrival_mode == "measured":
        raise ValueError(
            "checkpoint/resume is implemented for the scan trainer only; "
            "unset --arrival-mode measured"
        )
    deaths = _parse_deaths(kill_workers) if kill_workers else None
    if on_death != "error" and not deaths:
        raise ValueError("on_death requires kill_workers")
    if death_timeout is not None and on_death != "failover" \
            and elastic != "on":
        raise ValueError(
            "death_timeout only applies to on_death='failover' or "
            "elastic='on'"
        )
    if elastic == "on" and cfg.arrival_mode == "measured":
        raise ValueError("elastic needs the simulated-arrival trainer")
    if deaths and cfg.arrival_mode == "measured":
        raise ValueError("--kill-workers needs the simulated-arrival trainer")
    if deaths and (checkpoint_dir or resume):
        raise ValueError("--kill-workers does not compose with checkpointing")
    if deaths and on_death == "failover" and death_timeout is None:
        raise ValueError("--on-death failover requires --death-timeout")
    if deaths and not all(0 <= w < cfg.n_workers for w in deaths):
        raise ValueError(
            f"--kill-workers ids {sorted(deaths)} outside "
            f"[0, {cfg.n_workers})"
        )
    # telemetry resolution (utils/config.resolve_telemetry): flag > env >
    # off; "auto" = on exactly when the caller passed an explicit output
    # dir. Resolved BEFORE the default output_dir is synthesized so auto
    # keys off the user's request, not the fallback path.
    from erasurehead_tpu.utils.config import resolve_telemetry

    telemetry_on = resolve_telemetry(telemetry, output_dir is not None)
    if output_dir is None:
        # reference parity: results live beside the dataset,
        # <input_dir>/<dataset>/<W>/results/ (src/naive.py:200-202)
        base = dataset_dir(cfg) or "."
        output_dir = os.path.join(base, "results")

    initialize_distributed()
    dataset = load_dataset(cfg)
    import contextlib

    from erasurehead_tpu.obs import events as events_lib
    from erasurehead_tpu.utils.tracing import device_trace

    events_path = os.path.join(output_dir, "events.jsonl")
    capture = (
        events_lib.capture(events_path)
        if telemetry_on
        else contextlib.nullcontext()
    )
    with capture, device_trace(trace_dir):
        if elastic == "on":
            from erasurehead_tpu import elastic as elastic_lib

            ecfg_kw = dict(
                chunk_rounds=elastic_chunk, death_rounds=death_rounds,
                seed=cfg.seed,
            )
            if death_timeout is not None:
                ecfg_kw["timeout"] = death_timeout
            eres = elastic_lib.train_elastic_online(
                cfg, dataset,
                elastic=elastic_lib.ElasticConfig(**ecfg_kw),
                deaths=deaths,
                journal_dir=output_dir if telemetry_on else None,
            )
            result = eres.result
            if not quiet:
                relayouts = [
                    d for d in eres.decisions
                    if d["action"] == "relayout"
                ]
                print(
                    f"elastic membership: {len(eres.rows)} chunk(s), "
                    f"{len(relayouts)} re-layout(s) across "
                    f"{len(eres.epochs)} epoch(s)"
                )
                for d in eres.decisions:
                    print(
                        f"  round {d['round']:>4} {d['action']:10s} "
                        + str({
                            k: v for k, v in d.items()
                            if k not in ("round", "action")
                        })
                    )
        elif adapt == "on":
            from erasurehead_tpu import adapt as adapt_lib

            arms = _parse_arms(adapt_arms) if adapt_arms else None
            priors = None
            if adapt_priors:
                from erasurehead_tpu.whatif import Surface

                surface = Surface.load(adapt_priors)
                priors = surface.adapt_priors(
                    arms if arms is not None else adapt_lib.default_arms(cfg),
                    n_workers=cfg.n_workers,
                    n_stragglers=cfg.n_stragglers,
                )
                if not quiet:
                    print(
                        f"adapt priors <- {adapt_priors} "
                        f"(spec {surface.spec_hash}): "
                        f"{len(priors)} arm(s) primed"
                    )
            ares = adapt_lib.train_adaptive(
                cfg, dataset, arms=arms,
                controller=adapt_lib.ControllerConfig(
                    chunk_rounds=adapt_chunk, seed=cfg.seed
                ),
                priors=priors,
            )
            result = ares.result
            if not quiet:
                switches = sum(
                    1
                    for a, b in zip(ares.decisions, ares.decisions[1:])
                    if a["arm"] != b["arm"]
                )
                print(
                    f"adaptive collection: {len(ares.decisions)} "
                    f"decision(s), {switches} arm switch(es), "
                    f"{1000 * ares.decision_overhead_s:.2f} ms controller "
                    "overhead"
                )
                for d in ares.decisions:
                    print(
                        f"  chunk {d['chunk']:>3} -> {d['arm']:24s} "
                        f"[{d['reason']}]"
                    )
        elif cfg.arrival_mode == "measured":
            result = trainer.train_measured(cfg, dataset)
        elif deaths and on_death == "elastic":
            result, report = failures.train_elastic(cfg, dataset, deaths)
            if not quiet:
                print(
                    f"elastic restart at round {report.death_round}: "
                    f"{report.n_workers_before} -> "
                    f"{report.n_workers_after} workers "
                    f"(dead: {list(report.dead_workers)})"
                )
        elif deaths:
            # error|failover: inject the deaths into the arrival schedule
            # and plan the run; "error" raises where the reference's
            # master would block in Waitany forever
            arrivals = failures.inject_worker_death(
                trainer.default_arrivals(cfg), deaths
            )
            sched, _ = failures.plan_run(
                cfg.scheme,
                trainer.build_layout(cfg),
                arrivals,
                num_collect=cfg.num_collect,
                deadline=cfg.deadline,
                timeout=(
                    death_timeout if death_timeout is not None else np.inf
                ),
                on_infeasible=on_death,
            )
            result = trainer.train(
                cfg, dataset, arrivals=arrivals, schedule=sched
            )
        else:
            # a resumed run's artifacts cover [start_round, rounds) — the
            # loss curve is the resumed window, aligned by artifacts.py
            result = trainer.train(
                cfg,
                dataset,
                checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every,
                resume=resume,
            )
        model = trainer.build_model(cfg)
        n = result.n_train
        ev = evaluate.replay(
            model,
            cfg.model,
            result.params_history,
            dataset.X_train[:n],
            dataset.y_train[:n],
            dataset.X_test,
            dataset.y_test,
        )
        if result.run_id is not None:
            # the eval replay runs here, not in the trainer — attach its
            # summary to the run's event stream
            auc = float(ev.auc[-1])
            events_lib.emit(
                "eval",
                run_id=result.run_id,
                final_train_loss=float(ev.training_loss[-1]),
                final_test_loss=float(ev.testing_loss[-1]),
                final_auc=auc if np.isfinite(auc) else None,
            )
    paths = artifacts.write_run_artifacts(result, ev, output_dir)
    if telemetry_on:
        paths["events"] = events_path
    if not quiet:
        artifacts.print_iteration_table(result, ev)
        print(f"artifacts -> {output_dir}")
        if telemetry_on:
            print(f"events -> {events_path}")
    return result, ev, paths


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "report":
        # `erasurehead-tpu report <events.jsonl> ...` — render a run
        # telemetry event log into the human summary table (obs/report.py)
        from erasurehead_tpu.obs import report as report_lib

        return report_lib.main(argv[1:])
    if argv and argv[0] == "sweep":
        # `erasurehead-tpu sweep ...` — the comparison-suite sweep runner
        # (train/experiments.main), incl. --sweep-journal/--resume-sweep
        from erasurehead_tpu.train import experiments as experiments_lib

        return experiments_lib.main(argv[1:])
    if argv and argv[0] == "serve":
        # `erasurehead-tpu serve ...` — the multi-tenant sweep-as-a-service
        # daemon (erasurehead_tpu/serve/): packs concurrent clients'
        # compatible requests into shared cohort dispatches behind a unix
        # socket, under an HBM admission budget
        from erasurehead_tpu.serve import server as serve_lib

        return serve_lib.main(argv[1:])
    if argv and argv[0] == "fleet":
        # `erasurehead-tpu fleet ...` — N serve replicas behind a
        # consistent-hash router (erasurehead_tpu/serve/fleet.py):
        # evidential-streak membership over /healthz, WAL adoption when
        # a replica is declared dead, zero-downtime rolling deploys
        from erasurehead_tpu.serve import fleet as fleet_lib

        return fleet_lib.main(argv[1:])
    if argv and argv[0] == "whatif":
        # `erasurehead-tpu whatif ...` — the Monte-Carlo policy-search
        # engine (erasurehead_tpu/whatif/): grid spec -> batched cohort
        # simulation -> expected-time-to-target surface artifact
        from erasurehead_tpu.whatif import engine as whatif_lib

        return whatif_lib.main(argv[1:])
    if argv and argv[0] == "top":
        # `erasurehead-tpu top <events.jsonl|http://host:port> ...` — the
        # live terminal telemetry renderer (obs/exporter.top_main): tails
        # an event log (or polls a serve front's /metrics) through the
        # streaming reducer and redraws one summary frame per interval;
        # --slo-ttlr arms the per-tenant SLO burn-rate tracker
        from erasurehead_tpu.obs import exporter as exporter_lib

        return exporter_lib.top_main(argv[1:])
    if argv and argv[0] == "tune":
        # `erasurehead-tpu tune [--race ...] ...` — the measured
        # autotuning plane (erasurehead_tpu/tune/): races auto-gated
        # lowering pairs at a given run shape and persists the verdicts
        # to the JSON decision cache every `auto` knob resolves through.
        # Races run HERE (or in bench/smoke), never inside training
        # steps or serve dispatches.
        from erasurehead_tpu.tune import races as tune_races_lib

        return tune_races_lib.main(argv[1:])
    if argv and argv[0] == "lint":
        # `erasurehead-tpu lint [--strict] [paths]` — the AST invariant
        # analyzer (erasurehead_tpu/analysis/): trace-purity,
        # signature-completeness, registry-dispatch, event-schema and
        # donation-safety checks over the given files/dirs (default: the
        # installed package). Exit 0 = no unsuppressed findings.
        from erasurehead_tpu.analysis import runner as lint_lib

        return lint_lib.main(argv[1:])
    if len(argv) == 13 and not argv[0].startswith("-"):
        cfg = _legacy_to_config(argv)
        run(cfg)
        return 0
    parser = _flags_parser()
    ns = parser.parse_args(argv)
    _validate_checkpoint_flags(parser, ns)
    if ns.sweep_cache == "off":
        from erasurehead_tpu.train import cache as cache_lib

        cache_lib.set_enabled(False)
    cfg = _flags_to_config(ns)
    run(
        cfg,
        output_dir=ns.output_dir,
        quiet=ns.quiet,
        trace_dir=ns.trace_dir,
        checkpoint_dir=ns.checkpoint_dir,
        checkpoint_every=ns.checkpoint_every,
        resume=ns.resume,
        kill_workers=ns.kill_workers,
        on_death=ns.on_death,
        death_timeout=ns.death_timeout,
        telemetry=ns.telemetry,
        adapt=ns.adapt,
        adapt_chunk=ns.adapt_chunk,
        adapt_arms=ns.adapt_arms,
        adapt_priors=ns.adapt_priors,
        elastic=ns.elastic,
        elastic_chunk=ns.elastic_chunk,
        death_rounds=ns.death_rounds,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
