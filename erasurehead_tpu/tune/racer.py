"""Deterministic microbench racer: warm-up + min-over-repeats, tie->fallback.

The timing discipline is bench.py's: every candidate thunk runs once
unmeasured (compile + first-touch), then ``reps`` measured runs, and the
candidate's time is the MINIMUM — the least-noise estimator for a
deterministic program under scheduler jitter. A candidate only unseats
the hardcoded fallback by beating it by more than ``tie_margin``
(default 10%): within the margin the verdict is a tie and the fallback
stands, so run-to-run timer noise cannot flip a decision back and forth —
the determinism half of the acceptance bar. (The other half is the cache
serialization: tune/cache.py stores choices only, canonically ordered.)

``timer`` is injectable so tests race with a fake clock and assert exact
verdicts; production uses ``time.perf_counter``.

Races fire the ``tune_race`` chaos site before any timing — the
kill-mid-race drill (ERASUREHEAD_CHAOS=kill:tune_race:1) proves a torn
race leaves no partial cache entry (atomic writes) and a cold rerun
reproduces the uninterrupted verdict.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, Optional

from erasurehead_tpu.utils import chaos

#: a challenger must beat the fallback by this fraction to win "auto"
TIE_MARGIN = 0.10

#: measured repeats per candidate (min is taken)
DEFAULT_REPS = 3


@dataclasses.dataclass(frozen=True)
class RaceResult:
    """One settled race: the verdict plus the evidence."""

    race: str
    shape: str
    device_kind: str
    choice: str
    fallback: str
    timings: Dict[str, float]
    decisive: bool


def time_thunk(
    thunk: Callable[[], None],
    *,
    reps: int = DEFAULT_REPS,
    timer: Optional[Callable[[], float]] = None,
) -> float:
    """Warm once (compile/first-touch outside the clock), then min of
    ``reps`` timed runs."""
    timer = timer or time.perf_counter
    thunk()
    best = None
    for _ in range(max(1, reps)):
        t0 = timer()
        thunk()
        dt = timer() - t0
        best = dt if best is None else min(best, dt)
    return float(best)


def race(
    name: str,
    shape_sig: str,
    candidates: Dict[str, Callable[[], None]],
    *,
    fallback: str,
    device_kind: Optional[str] = None,
    reps: int = DEFAULT_REPS,
    tie_margin: float = TIE_MARGIN,
    timer: Optional[Callable[[], float]] = None,
    record: bool = True,
    cache=None,
) -> RaceResult:
    """Race ``candidates`` (name -> thunk) and settle the verdict.

    The winner is recorded into the decision cache (unless
    ``record=False``) and emitted as a typed ``tune`` event with
    ``source="race"``. Candidates time in sorted-name order, so the
    measurement schedule itself is deterministic.
    """
    from erasurehead_tpu import tune as tune_lib

    if fallback not in candidates:
        raise ValueError(
            f"race {name!r}: fallback {fallback!r} not among candidates "
            f"{sorted(candidates)}"
        )
    chaos.maybe_fire("tune_race")
    dk = device_kind or tune_lib.default_device_kind()
    timings = {
        cname: time_thunk(candidates[cname], reps=reps, timer=timer)
        for cname in sorted(candidates)
    }
    best = min(sorted(timings), key=lambda k: timings[k])
    decisive = (
        best != fallback
        and timings[best] < timings[fallback] * (1.0 - tie_margin)
    )
    choice = best if decisive else fallback
    if record:
        (cache or tune_lib.get_cache()).record(dk, name, shape_sig, choice)
    tune_lib.emit_decision(name, dk, shape_sig, choice, "race")
    return RaceResult(
        race=name, shape=shape_sig, device_kind=dk, choice=choice,
        fallback=fallback, timings=timings, decisive=decisive,
    )
