"""Measured autotuning plane (ISSUE 19): race, cache, resolve.

Every perf ``auto`` knob in this repo used to bottom out in a hardcoded
constant pinned by a one-off measurement (step.RING_PIPELINE_DEFAULT,
LAYER_CODING_DEFAULT, BLOCK_DECODE_FUSED_DEFAULT, kernels.supports_fused,
sharding.RING_AUTO_MIN_BYTES). This package replaces the *bare constant*
with a resolution ladder:

    explicit knob > env override > cached measured decision > constant

The measured decisions come from deterministic microbench races
(tune/racer.py discipline: seeded inputs, warm-up, min-over-repeats,
tie->fallback) run at the run's ACTUAL shape — by ``erasurehead-tpu
tune``, the bench ``tune`` extra, or ``make tune-smoke`` — and persist in
a JSON decision cache keyed by ``(device_kind, race, shape signature)``
(tune/cache.py). Warm runs never re-race: resolution is one memoized
dict lookup (<1 ms, the acceptance bar). Races NEVER run inside a
training step, a request dispatch (serve preloads its per-daemon cache
at startup), or a resolver — resolvers only read.

Resolutions are observable as typed ``tune`` events (obs/events.py
SCHEMA; source "race"/"cache"/"default"), deduplicated per process, and
bitwise-invariant to telemetry on/off — emission never feeds back into
the resolved choice.

Races and their choice vocabularies (TUNE_RACES / TUNE_CHOICES):

    block_decode   fused | treewise   (blockwise decode lowering)
    layer_coding   blockwise | treewise  (per-layer coding on/off)
    glm_fused      pallas | xla       (fused GLM kernel vs XLA two-pass)
    ring_pipeline  pipelined | sequential (ring transport schedule)
    stack_mode     ring | materialized   (faithful stack residency)
"""

from __future__ import annotations

from typing import Optional

from erasurehead_tpu.tune.cache import (  # noqa: F401 (public API)
    DecisionCache,
    ENV_PATH,
    canonical_bytes,
    decision_key,
    default_path,
    get_cache,
    reset,
)

#: every race the plane knows, with its candidate vocabulary — the
#: events validator checks membership (obs/events.TUNE_RACES mirrors the
#: keys; lint pins the two against drift via the schema fixture tests)
TUNE_CHOICES = {
    "block_decode": ("fused", "treewise"),
    "layer_coding": ("blockwise", "treewise"),
    "glm_fused": ("pallas", "xla"),
    "ring_pipeline": ("pipelined", "sequential"),
    "stack_mode": ("ring", "materialized"),
}

RACES = tuple(sorted(TUNE_CHOICES))


def default_device_kind() -> str:
    """The cache's device dimension: TPU generation string on silicon
    (decisions must not leak across v5e/v6e), platform name elsewhere."""
    try:
        import jax

        d = jax.devices()[0]
        return str(getattr(d, "device_kind", None) or d.platform)
    except Exception:  # noqa: BLE001 — no backend == no measured plane
        return "unknown"


def run_shape_signature(model, X) -> str:
    """The shape key a run resolves (and races) under: model family +
    depth + the materialized stack's type/shape/dtype. Must be
    computable both at resolution time (trainer has model + stack) and
    at race time (trainer.resolved_stack builds the same pair)."""
    shape = tuple(int(s) for s in getattr(X, "shape", ()))
    dtype = str(getattr(X, "dtype", "?"))
    nl = getattr(model, "n_layers", None)
    return (
        f"model={type(model).__name__}"
        f"|nl={nl}|X={type(X).__name__}{shape}|{dtype}"
    )


def glm_fused_signature(shape, dtype, kind: str) -> str:
    """Shape key of the fused-GLM race (ops/kernels.supports_fused)."""
    return f"glm={kind}|X={tuple(int(s) for s in shape)}|{dtype}"


def stack_mode_signature(layout, rows: int, n_features: int, dtype) -> str:
    """Shape key of the stack-residency race (data/sharding.
    resolve_ring_stack): the pre-stack quantities the footprint gate
    reads — no materialized array exists yet when this resolves."""
    return (
        f"W={layout.n_workers}|P={layout.n_partitions}"
        f"|S={layout.n_slots}|rows={int(rows)}|F={int(n_features)}"
        f"|{dtype}"
    )


# -- typed tune events, deduplicated per process ----------------------------

_emitted: set = set()


def emit_decision(
    race: str, device_kind: str, shape: str, choice: str, source: str
) -> None:
    """Emit one ``tune`` event per distinct decision per process.

    Observation only: emission happens after the choice is made and never
    feeds back — telemetry on/off stays bitwise on tuned runs."""
    key = (race, device_kind, shape, choice, source)
    if key in _emitted:
        return
    _emitted.add(key)
    from erasurehead_tpu.obs import events as events_lib

    events_lib.emit(
        "tune", race=race, device_kind=device_kind, shape=shape,
        choice=choice, source=source,
    )


def reset_emitted() -> None:
    """Tests: forget the per-process event dedup."""
    _emitted.clear()


def lookup(
    race: str,
    shape_sig: str,
    device_kind: Optional[str] = None,
    fallback: Optional[str] = None,
) -> Optional[str]:
    """Resolve one auto knob: cached decision or None (caller's constant).

    The single consult point every resolver goes through
    (step.resolve_ring_pipeline / resolve_layer_coding /
    resolve_block_decode, kernels.supports_fused,
    sharding.resolve_ring_stack). Warm path: one stat(2) + dict lookup.
    Emits the decision as a ``tune`` event — ``source="cache"`` when a
    verdict applies, ``source="default"`` (with ``fallback`` as the
    choice, when given) when the hardcoded constant stands."""
    dk = device_kind or default_device_kind()
    choice = get_cache().lookup(dk, race, shape_sig)
    if choice is not None:
        emit_decision(race, dk, shape_sig, choice, "cache")
        return choice
    if fallback is not None:
        emit_decision(race, dk, shape_sig, fallback, "default")
    return None
