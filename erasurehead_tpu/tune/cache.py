"""JSON decision cache for measured autotuning verdicts (ISSUE 19).

One file, one dict: ``{"version": 1, "decisions": {"<device_kind>|<race>|
<shape signature>": {"choice": "<candidate>"}}}``. The cache stores ONLY
the verdicts — never timings, timestamps, or host names — so two races at
the same shapes with the same seeds serialize to byte-identical files
(the determinism acceptance bar) and a cache file is portable review
material: the diff of a default flip is one line of JSON.

Writes are atomic (tmp file + ``os.replace`` in the cache's directory), so
a run killed mid-race (chaos site ``tune_race``) leaves either the old
complete file or the new complete file, never a torn one — the
kill->rerun invariance test pins this. Reads tolerate a missing or
corrupt file as an empty cache (the resolver falls back to the hardcoded
default, exactly as if the race never ran).

Lookups are warm-path cheap: the parsed decisions are memoized per
process and re-read only when the file's (mtime_ns, size) stamp moves —
one ``stat(2)`` per resolution, no JSON parse. The serve daemon preloads
its per-daemon cache at startup so no request dispatch ever races or
parses (serve/server.py).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Optional

#: env override for the cache file location (tests, smokes, CI isolation)
ENV_PATH = "ERASUREHEAD_TUNE_CACHE"

VERSION = 1


def default_path() -> str:
    env = os.environ.get(ENV_PATH)
    if env:
        return env
    return os.path.join(
        os.path.expanduser("~"), ".cache", "erasurehead_tpu", "tune.json"
    )


def decision_key(device_kind: str, race: str, shape_sig: str) -> str:
    return f"{device_kind}|{race}|{shape_sig}"


def canonical_bytes(decisions: dict) -> bytes:
    """The one serialization of a decision dict: sorted keys, fixed
    separators, trailing newline — byte-identical for equal decisions."""
    doc = {
        "version": VERSION,
        "decisions": {
            k: {"choice": decisions[k]} for k in sorted(decisions)
        },
    }
    return (json.dumps(doc, indent=2, sort_keys=True) + "\n").encode()


class DecisionCache:
    """The decisions behind every resolved ``auto`` knob, as a file."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._decisions: dict = {}
        self._stamp: Optional[tuple] = None

    def _refresh_locked(self) -> None:
        try:
            st = os.stat(self.path)
            stamp = (st.st_mtime_ns, st.st_size)
        except OSError:
            self._decisions, self._stamp = {}, None
            return
        if stamp == self._stamp:
            return
        try:
            with open(self.path) as f:
                doc = json.load(f)
            decisions = {
                str(k): str(v["choice"])
                for k, v in doc.get("decisions", {}).items()
                if isinstance(v, dict) and "choice" in v
            }
        except (OSError, ValueError, KeyError, TypeError):
            # corrupt/unreadable file == empty cache: the resolver falls
            # back to the hardcoded default rather than failing the run
            decisions = {}
        self._decisions, self._stamp = decisions, stamp

    def lookup(
        self, device_kind: str, race: str, shape_sig: str
    ) -> Optional[str]:
        with self._lock:
            self._refresh_locked()
            return self._decisions.get(
                decision_key(device_kind, race, shape_sig)
            )

    def decisions(self) -> dict:
        with self._lock:
            self._refresh_locked()
            return dict(self._decisions)

    def record(
        self, device_kind: str, race: str, shape_sig: str, choice: str
    ) -> None:
        with self._lock:
            self._refresh_locked()
            self._decisions[
                decision_key(device_kind, race, shape_sig)
            ] = str(choice)
            self._write_locked()

    def _write_locked(self) -> None:
        d = os.path.dirname(self.path) or "."
        os.makedirs(d, exist_ok=True)
        data = canonical_bytes(self._decisions)
        fd, tmp = tempfile.mkstemp(prefix=".tune-", dir=d)
        closed = False
        try:
            os.write(fd, data)
            os.fsync(fd)
            os.close(fd)
            closed = True
            os.replace(tmp, self.path)
        except BaseException:
            if not closed:
                os.close(fd)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        try:
            st = os.stat(self.path)
            self._stamp = (st.st_mtime_ns, st.st_size)
        except OSError:
            self._stamp = None

    def to_bytes(self) -> bytes:
        with self._lock:
            self._refresh_locked()
            return canonical_bytes(self._decisions)


_caches: dict = {}
_caches_lock = threading.Lock()


def get_cache(path: Optional[str] = None) -> DecisionCache:
    """Process-global memoized cache per path (the serve daemon holds its
    own per-daemon instance instead — serve/server.py)."""
    p = path or default_path()
    with _caches_lock:
        c = _caches.get(p)
        if c is None:
            c = _caches[p] = DecisionCache(p)
        return c


def reset() -> None:
    """Drop memoized caches (tests switching ERASUREHEAD_TUNE_CACHE)."""
    with _caches_lock:
        _caches.clear()
