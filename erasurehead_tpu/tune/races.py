"""The concrete auto-knob races, each at a run's ACTUAL shape.

Every race here is end-to-end honest: the candidates are two fully wired
``trainer.train`` configurations (or, for ``glm_fused``, the two jitted
gradient lowerings) differing ONLY in the knob under test, timed with the
racer's warm-up + min-over-repeats discipline on seeded synthetic data.
Racing whole short runs rather than isolated bodies is deliberate — this
repo's history is littered with profile-favored lowerings that lost
end-to-end races (FLAT_GRAD_DEFAULT, supports_fused), so the verdicts
that flip defaults must be the end-to-end ones.

Races that need hardware this host lacks (ring transport across >= 2
devices) SKIP — they return None, record nothing, and the resolver keeps
its hardcoded fallback. A skipped race is not a verdict.

``erasurehead-tpu tune`` (cli.py) drives these from flags; ``make
tune-smoke`` and the bench ``tune`` extra drive them in-process.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from erasurehead_tpu.tune import racer as racer_lib


def _replace(cfg, **over):
    return dataclasses.replace(cfg, **over)


def _dataset(cfg):
    from erasurehead_tpu.data.synthetic import generate_gmm

    return generate_gmm(
        cfg.n_rows, cfg.n_cols, n_partitions=cfg.n_workers, seed=cfg.seed
    )


def _train_thunk(cfg, dataset):
    from erasurehead_tpu.train import trainer

    def thunk():
        trainer.train(cfg, dataset)

    return thunk


def _signature(cfg, dataset) -> str:
    from erasurehead_tpu import tune as tune_lib
    from erasurehead_tpu.train import trainer

    model, X = trainer.resolved_stack(cfg, dataset)
    return tune_lib.run_shape_signature(model, X)


def race_block_decode(
    cfg, dataset=None, *, reps: int = racer_lib.DEFAULT_REPS,
    timer=None, record: bool = True,
) -> racer_lib.RaceResult:
    """Treewise pack-then-einsum vs fused per-leaf decode, blockwise
    coding forced on (the lowering pair behind resolve_block_decode).
    Bitwise-identical trajectories — the race is purely about time."""
    from erasurehead_tpu.parallel import step as step_lib

    dataset = dataset if dataset is not None else _dataset(cfg)
    base = _replace(cfg, layer_coding="on")
    sig = _signature(base, dataset)
    fallback = (
        "fused" if step_lib.BLOCK_DECODE_FUSED_DEFAULT else "treewise"
    )
    return racer_lib.race(
        "block_decode", sig,
        {
            "treewise": _train_thunk(
                _replace(base, block_decode="treewise"), dataset
            ),
            "fused": _train_thunk(
                _replace(base, block_decode="fused"), dataset
            ),
        },
        fallback=fallback, reps=reps, timer=timer, record=record,
    )


def race_layer_coding(
    cfg, dataset=None, *, reps: int = racer_lib.DEFAULT_REPS,
    timer=None, record: bool = True,
) -> racer_lib.RaceResult:
    """Per-layer blockwise decode vs the treewise per-slot default (the
    pair behind resolve_layer_coding's auto)."""
    from erasurehead_tpu.parallel import step as step_lib

    dataset = dataset if dataset is not None else _dataset(cfg)
    sig = _signature(_replace(cfg, layer_coding="off"), dataset)
    fallback = (
        "blockwise" if step_lib.LAYER_CODING_DEFAULT else "treewise"
    )
    return racer_lib.race(
        "layer_coding", sig,
        {
            "treewise": _train_thunk(
                _replace(cfg, layer_coding="off"), dataset
            ),
            "blockwise": _train_thunk(
                _replace(cfg, layer_coding="on"), dataset
            ),
        },
        fallback=fallback, reps=reps, timer=timer, record=record,
    )


def race_glm_fused(
    cfg, dataset=None, *, reps: int = racer_lib.DEFAULT_REPS,
    timer=None, record: bool = True,
) -> racer_lib.RaceResult:
    """Fused pallas GLM kernel vs XLA's two-pass lowering, at the run's
    slot-stack shape (the pair behind kernels.supports_fused). On
    non-TPU hosts the kernel runs in interpret mode — it will lose, and
    recording that loss is correct: supports_fused declines off-TPU
    anyway, and the cache key is per device_kind."""
    import jax
    import jax.numpy as jnp

    from erasurehead_tpu import tune as tune_lib
    from erasurehead_tpu.ops import kernels as kernels_lib
    from erasurehead_tpu.train import trainer

    dataset = dataset if dataset is not None else _dataset(cfg)
    model, X = trainer.resolved_stack(cfg, dataset)
    kind = getattr(model, "name", "logistic")
    if kind not in kernels_lib.GLM_KINDS or not isinstance(X, jax.Array):
        raise ValueError(
            f"glm_fused race needs a dense GLM stack; got model={kind!r}, "
            f"X={type(X).__name__} (set --model logistic/linear)"
        )
    sig = tune_lib.glm_fused_signature(X.shape, str(X.dtype), kind)
    lead = X.shape[:-2]
    M = 1
    for s in lead:
        M *= int(s)
    Xf = X.reshape((M,) + X.shape[-2:])
    import numpy as np

    rng = np.random.default_rng(cfg.seed)
    y = jnp.asarray(
        np.sign(rng.standard_normal(Xf.shape[:2])), Xf.dtype
    ).astype(jnp.float32)
    b = jnp.asarray(rng.standard_normal(Xf.shape[-1]), jnp.float32)
    w = jnp.asarray(rng.standard_normal(M), jnp.float32)
    interpret = jax.devices()[0].platform != "tpu"
    pallas_fn = jax.jit(
        lambda: kernels_lib.fused_glm_grad(
            b, Xf, y, w, kind, interpret=interpret
        )
    )
    xla_fn = jax.jit(
        lambda: kernels_lib.reference_glm_grad(b, Xf, y, w, kind)
    )
    return racer_lib.race(
        "glm_fused", sig,
        {
            "pallas": lambda: jax.block_until_ready(pallas_fn()),
            "xla": lambda: jax.block_until_ready(xla_fn()),
        },
        fallback="xla", reps=reps, timer=timer, record=record,
    )


def race_ring_pipeline(
    cfg, dataset=None, *, reps: int = racer_lib.DEFAULT_REPS,
    timer=None, record: bool = True,
) -> Optional[racer_lib.RaceResult]:
    """Sequential vs double-buffered ring transport, stack_mode=ring
    forced (the pair behind resolve_ring_pipeline). Skips (None) on a
    single-device host: a one-hop ring times nothing real."""
    import jax

    if len(jax.devices()) < 2:
        return None
    dataset = dataset if dataset is not None else _dataset(cfg)
    base = _replace(cfg, stack_mode="ring")
    sig = _signature(base, dataset)
    return racer_lib.race(
        "ring_pipeline", sig,
        {
            "sequential": _train_thunk(
                _replace(base, ring_pipeline="off"), dataset
            ),
            "pipelined": _train_thunk(
                _replace(base, ring_pipeline="on"), dataset
            ),
        },
        fallback="sequential", reps=reps, timer=timer, record=record,
    )


def race_stack_mode(
    cfg, dataset=None, *, reps: int = racer_lib.DEFAULT_REPS,
    timer=None, record: bool = True,
) -> Optional[racer_lib.RaceResult]:
    """Materialized faithful stack vs ring-streamed, at the footprint
    boundary (the pair behind resolve_ring_stack's auto threshold).
    Skips on a single-device host for the same reason as ring_pipeline.
    Keyed by the PRE-stack signature (tune.stack_mode_signature): the
    resolver runs before any stack exists."""
    import jax

    from erasurehead_tpu import tune as tune_lib
    from erasurehead_tpu.train import trainer

    if len(jax.devices()) < 2:
        return None
    dataset = dataset if dataset is not None else _dataset(cfg)
    layout = trainer.build_layout(cfg)
    sig = tune_lib.stack_mode_signature(
        layout, dataset.n_samples // layout.n_partitions,
        cfg.n_cols, cfg.dtype,
    )
    return racer_lib.race(
        "stack_mode", sig,
        {
            "materialized": _train_thunk(
                _replace(cfg, stack_mode="materialized"), dataset
            ),
            "ring": _train_thunk(
                _replace(cfg, stack_mode="ring"), dataset
            ),
        },
        fallback="materialized", reps=reps, timer=timer, record=record,
    )


RACE_FNS = {
    "block_decode": race_block_decode,
    "layer_coding": race_layer_coding,
    "glm_fused": race_glm_fused,
    "ring_pipeline": race_ring_pipeline,
    "stack_mode": race_stack_mode,
}


def main(argv=None) -> int:
    """``erasurehead-tpu tune`` — race auto knobs at a given shape and
    persist the verdicts to the decision cache.

    The races run HERE, once, explicitly — never inside training steps or
    serve dispatches. Warm runs then resolve from the cache file this
    writes (override the location with ERASUREHEAD_TUNE_CACHE)."""
    import argparse

    from erasurehead_tpu import tune as tune_lib
    from erasurehead_tpu.utils.config import RunConfig

    p = argparse.ArgumentParser(
        prog="erasurehead-tpu tune",
        description=(
            "race auto-gated lowerings at a run shape; verdicts persist "
            "to the tune decision cache"
        ),
    )
    p.add_argument(
        "--race", action="append", choices=sorted(RACE_FNS) + ["all"],
        default=None,
        help="race(s) to run (repeatable; default: block_decode)",
    )
    p.add_argument("--scheme", default="approx")
    p.add_argument("--model", default="deepmlp")
    p.add_argument("--workers", type=int, default=8)
    p.add_argument("--stragglers", type=int, default=1)
    p.add_argument("--num-collect", type=int, default=6)
    p.add_argument("--rounds", type=int, default=8)
    p.add_argument("--rows", type=int, default=256)
    p.add_argument("--cols", type=int, default=32)
    p.add_argument("--deep-layers", type=int, default=0)
    p.add_argument("--dtype", default="float32")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--reps", type=int, default=racer_lib.DEFAULT_REPS)
    p.add_argument(
        "--json", action="store_true",
        help="print ONE JSON result line (tools/measure_lib.sh capture "
             "discipline: carries a 'platform' field) instead of the "
             "human verdict lines",
    )
    ns = p.parse_args(argv)

    names = ns.race or ["block_decode"]
    if "all" in names:
        names = sorted(RACE_FNS)
    cfg = RunConfig(
        scheme=ns.scheme, model=ns.model, n_workers=ns.workers,
        n_stragglers=ns.stragglers, num_collect=ns.num_collect,
        rounds=ns.rounds, n_rows=ns.rows, n_cols=ns.cols,
        lr_schedule=0.5, update_rule="AGD", add_delay=True,
        seed=ns.seed, deep_layers=ns.deep_layers, dtype=ns.dtype,
    )
    dataset = _dataset(cfg)
    if not ns.json:
        print(f"tune cache: {tune_lib.default_path()}")
    results = {}
    for name in names:
        res = RACE_FNS[name](cfg, dataset, reps=ns.reps)
        if res is None:
            results[name] = None
            if not ns.json:
                print(f"{name}: SKIPPED (needs >= 2 devices)")
            continue
        results[name] = res
        if ns.json:
            continue
        timings = "  ".join(
            f"{k}={v * 1e3:.2f}ms" for k, v in sorted(res.timings.items())
        )
        verdict = "decisive" if res.decisive else "tie -> fallback"
        print(
            f"{name}: choice={res.choice} ({verdict})  [{timings}]  "
            f"shape={res.shape}"
        )
    if ns.json:
        import json

        import jax

        print(json.dumps({
            "metric": "tune_races",
            "platform": jax.devices()[0].platform,
            "device_kind": tune_lib.default_device_kind(),
            "cache": tune_lib.default_path(),
            "races": {
                name: (
                    None if res is None else {
                        "choice": res.choice,
                        "fallback": res.fallback,
                        "decisive": res.decisive,
                        "shape": res.shape,
                        "timings_ms": {
                            k: round(v * 1e3, 3)
                            for k, v in sorted(res.timings.items())
                        },
                    }
                )
                for name, res in results.items()
            },
        }))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
