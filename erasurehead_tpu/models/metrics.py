"""Evaluation metrics: logistic loss, MSE, ROC AUC.

The reference evaluates post-hoc on the master with numpy + sklearn
(src/naive.py:184-198; src/util.py:136-141). We provide the same three
metrics twice: a jit-compatible jnp form (for on-device eval replay of the
whole iterate history at once) and an sklearn-backed host form used by the
artifact writer for exact parity with the reference's reported numbers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["log_loss_mean", "mse_mean", "auc", "auc_sklearn"]


def log_loss_mean(y: jnp.ndarray, margins: jnp.ndarray) -> jnp.ndarray:
    """Mean logistic loss, labels in {-1,+1} (src/util.py:136-137).

    Uses softplus rather than the reference's literal log(1+exp(.)), which
    overflows float32 for margins beyond ~88.
    """
    return jnp.mean(jax.nn.softplus(-y * margins))


def mse_mean(y: jnp.ndarray, pred: jnp.ndarray) -> jnp.ndarray:
    """Mean squared error (src/util.py:139-141)."""
    return jnp.mean((y - pred) ** 2)


def auc(y: jnp.ndarray, scores: jnp.ndarray) -> jnp.ndarray:
    """ROC AUC via the Mann-Whitney U statistic, jit/TPU-compatible.

    Equals sklearn's trapezoidal roc_curve/auc (src/naive.py:188-197) exactly
    when scores are tie-free; ties are handled by midranks (sklearn
    equivalent).
    """
    pos = y > 0
    n_pos = jnp.sum(pos)
    n_neg = y.shape[0] - n_pos
    order = jnp.argsort(scores)
    sorted_scores = scores[order]
    ranks_sorted = jnp.arange(1, y.shape[0] + 1, dtype=scores.dtype)
    # Midranks for ties, without jnp.unique (dynamic-shape, not jit-friendly):
    # average the rank over each run of equal sorted scores via segment sums.
    same_as_prev = jnp.concatenate(
        [jnp.zeros(1, bool), sorted_scores[1:] == sorted_scores[:-1]]
    )
    # group id for each run of equal scores
    group = jnp.cumsum(~same_as_prev) - 1
    group_sum = jax.ops.segment_sum(
        ranks_sorted, group, num_segments=y.shape[0]
    )
    group_cnt = jax.ops.segment_sum(
        jnp.ones_like(ranks_sorted), group, num_segments=y.shape[0]
    )
    midrank_sorted = group_sum[group] / group_cnt[group]
    ranks = jnp.zeros_like(midrank_sorted).at[order].set(midrank_sorted)
    rank_sum_pos = jnp.sum(jnp.where(pos, ranks, 0.0))
    u = rank_sum_pos - n_pos * (n_pos + 1) / 2.0
    return u / (n_pos * n_neg)


def auc_sklearn(y: np.ndarray, scores: np.ndarray) -> float:
    """Exact reference parity: sklearn roc_curve + auc (src/naive.py:188-197)."""
    from sklearn.metrics import auc as _auc
    from sklearn.metrics import roc_curve

    fpr, tpr, _ = roc_curve(np.asarray(y), np.asarray(scores), pos_label=1)
    return float(_auc(fpr, tpr))
