"""Mixture-of-experts classifier: the expert-parallel stretch family.

The last classic parallelism axis the reference lacks a model for
(SURVEY.md §2.2 lists EP absent — no MoE anywhere). This family supplies
one: ``n_experts`` small tanh expert MLPs plus a learned softmax gate,
margins = sum_e gate_e(x) * expert_e(x) — the dense ("soft") MoE form, so
the decoded gradient stays exact and every-scheme-compatible (hard top-k
routing drops experts per row, which would break the coded-DP exactness
story this framework's tests pin; the EP *sharding* pattern is identical).

``ep_axis`` composes expert parallelism with the coded DP on a 2-D
(workers, expert) mesh (``--ep-shards``): expert parameters are stacked
[E, ...] and each member computes only its contiguous block of experts'
outputs, weighted by the (replicated, tiny) gate; partial margins psum
over the expert axis — identical margins on every member, so gradients
ride the same weighted-scalar-loss path as the seq/TP/PP modes
(parallel/step._weighted_loss_grad) and come out exact by shard_map's
replicated-param cotangent rules. Pinned against the unsharded oracle and
trajectory-equal in tests, like every other composed axis.
"""

from __future__ import annotations

import jax
from erasurehead_tpu.utils import compat
import jax.numpy as jnp
from jax import lax

from erasurehead_tpu.models.glm import MarginClassifierBase
from erasurehead_tpu.ops.features import matvec

EXPERT_AXIS = "expert"


class MoEModel(MarginClassifierBase):
    name = "moe"
    # per-layer gradient coding (ops/blocks.py): every expert-stacked
    # leaf splits along the expert axis, so each expert shard's gradient
    # is its own coded block — the experts are the natural partitions of
    # the coded decode (ROADMAP item 4); the tiny gate stays one block
    block_split_leaves = ("W1", "b1", "w2", "b2")

    def __init__(
        self,
        hidden: int = 16,
        n_experts: int = 4,
        ep_axis: str | None = None,
    ):
        self.hidden = hidden
        self.n_experts = n_experts
        # when set, predict must run inside a shard_map whose mesh carries
        # this axis (the trainer's for_mesh hook arranges it)
        self.ep_axis = ep_axis

    def for_mesh(self, mesh):
        """Trainer hook: an expert-parallel copy when the mesh has an
        expert axis (scoped to step construction; eval stays unsharded)."""
        from erasurehead_tpu.parallel.mesh import axis_active

        if axis_active(mesh, EXPERT_AXIS):
            return MoEModel(self.hidden, self.n_experts, ep_axis=EXPERT_AXIS)
        return self

    def init_params(self, key: jax.Array, n_features: int):
        ks = jax.random.split(key, 4)
        E, H = self.n_experts, self.hidden
        return {
            # per-expert 2-layer MLPs, stacked on the expert dim
            "W1": jax.random.normal(ks[0], (E, n_features, H))
            / jnp.sqrt(n_features),
            "b1": jnp.zeros((E, H)),
            "w2": jax.random.normal(ks[1], (E, H)) / jnp.sqrt(H),
            "b2": jnp.zeros(E),
            # the gate is tiny and replicated everywhere
            "Wg": jax.random.normal(ks[2], (n_features, E))
            / jnp.sqrt(n_features),
            "bg": jnp.zeros(E),
        }

    def _expert_margins(self, params, X, lo, count):
        """[n, count] margins of experts lo..lo+count-1 (count static)."""
        outs = []
        for j in range(count):
            W1 = lax.dynamic_index_in_dim(params["W1"], lo + j, keepdims=False)
            b1 = lax.dynamic_index_in_dim(params["b1"], lo + j, keepdims=False)
            w2 = lax.dynamic_index_in_dim(params["w2"], lo + j, keepdims=False)
            b2 = lax.dynamic_index_in_dim(params["b2"], lo + j, keepdims=False)
            h = jnp.tanh(matvec(X, W1) + b1)
            outs.append(h @ w2 + b2)
        return jnp.stack(outs, axis=1)

    def _gate(self, params, X):
        return jax.nn.softmax(matvec(X, params["Wg"]) + params["bg"], axis=1)

    def predict(self, params, X):
        if self.ep_axis is not None:
            return self._predict_ep(params, X)
        E = self.n_experts
        gate = self._gate(params, X)  # [n, E]
        margins_e = self._expert_margins(params, X, 0, E)  # [n, E]
        return jnp.sum(gate * margins_e, axis=1)

    def _predict_ep(self, params, X):
        """Expert-parallel forward: this member evaluates only its block
        of experts; gate-weighted partial margins psum over the axis."""
        ax = self.ep_axis
        p = compat.axis_size(ax)
        E = self.n_experts
        if E % p:
            raise ValueError(f"n_experts={E} must divide over {p} ep shards")
        per = E // p
        i = lax.axis_index(ax)
        gate = self._gate(params, X)  # [n, E] (tiny, replicated compute)
        gate_l = lax.dynamic_slice_in_dim(gate, i * per, per, axis=1)
        margins_l = self._expert_margins(params, X, i * per, per)  # [n, per]
        return lax.psum(jnp.sum(gate_l * margins_l, axis=1), ax)
