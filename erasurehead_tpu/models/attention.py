"""Single-block attention classifier: the sequence-model stretch family.

The reference trains only convex GLMs (SURVEY.md §2.2); the MLP showed the
coded-DP machinery is model-agnostic for pytree params. This model closes
the remaining loop: a TRANSFORMER-STYLE model — embedding, one self-attention
block (parallel/ring.py's oracle form), mean pooling, logistic head — trained
under the exact same gradient-coding protocol, because its summed loss is
additive over row shards like every other model here.

Each data row is a sequence: the flat feature vector [F] reshapes to
[T, D] with T = F // d_in tokens (no change to the Dataset/sharding layers;
the reference's row-sharded DP carries over unchanged). DP shards rows
across workers; when a single sequence must span chips instead, the
attention inside is exactly what parallel/ring.py's ring/Ulysses primitives
shard — composing SP with this DP is the documented scale-out path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from erasurehead_tpu.models.glm import MarginClassifierBase
from erasurehead_tpu.ops.features import FieldOnehot, PaddedRows
from erasurehead_tpu.parallel.ring import reference_attention


class AttentionModel(MarginClassifierBase):
    name = "attention"

    def __init__(self, d_in: int = 8, d_model: int = 16):
        self.d_in = d_in
        self.d_model = d_model

    def init_params(self, key: jax.Array, n_features: int):
        if n_features % self.d_in:
            raise ValueError(
                f"n_features={n_features} must be divisible by d_in={self.d_in} "
                f"(rows reshape to [T, {self.d_in}] token sequences)"
            )
        ks = jax.random.split(key, 5)
        d, m = self.d_in, self.d_model
        s_in = 1.0 / jnp.sqrt(d)
        s_m = 1.0 / jnp.sqrt(m)
        return {
            "embed": s_in * jax.random.normal(ks[0], (d, m)),
            "wq": s_m * jax.random.normal(ks[1], (m, m)),
            "wk": s_m * jax.random.normal(ks[2], (m, m)),
            "wv": s_m * jax.random.normal(ks[3], (m, m)),
            "w_out": s_m * jax.random.normal(ks[4], (m,)),
            "b_out": jnp.zeros(()),
        }

    def predict(self, params, X):
        if isinstance(X, (PaddedRows, FieldOnehot)):
            raise TypeError(
                "the attention model requires dense features (rows reshape "
                "to token sequences); sparse data is not supported"
            )
        Xd = jnp.asarray(X).astype(jnp.float32)
        n, F = Xd.shape
        tokens = Xd.reshape(n, F // self.d_in, self.d_in)
        h = tokens @ params["embed"]  # [n, T, m]

        def attend(hseq):
            q, k, v = (
                hseq @ params["wq"],
                hseq @ params["wk"],
                hseq @ params["wv"],
            )
            return reference_attention(q, k, v)

        a = jax.vmap(attend)(h)  # [n, T, m]
        pooled = (h + a).mean(axis=1)  # residual + mean pool, [n, m]
        return pooled @ params["w_out"] + params["b_out"]
