"""Single-block attention classifier: the sequence-model stretch family.

The reference trains only convex GLMs (SURVEY.md §2.2); the MLP showed the
coded-DP machinery is model-agnostic for pytree params. This model closes
the remaining loop: a TRANSFORMER-STYLE model — embedding, one self-attention
block (parallel/ring.py's oracle form), mean pooling, logistic head — trained
under the exact same gradient-coding protocol, because its summed loss is
additive over row shards like every other model here.

Each data row is a sequence: the flat feature vector [F] reshapes to
[T, D] with T = F // d_in tokens (no change to the Dataset/sharding layers;
the reference's row-sharded DP carries over unchanged). DP shards rows
across workers; ``seq_axis`` composes SP with that DP on a 2-D mesh
(parallel/mesh.worker_seq_mesh): each seq member takes its token slice of
the locally-sharded rows, attention spans the seq axis in either canonical
SP form (``sp_form``) — "ring" (K/V rotate via lax.ppermute under
lax.scan) or "ulysses" (one all_to_all to head-sharded full sequences,
plain attention per head, one back; needs n_heads % seq_shards == 0) —
and the mean pool psums partial token sums so margins are identical on
every member. Gradients under the coded step come from ONE jax.grad of
the weighted scalar loss per device (parallel/step._weighted_loss_grad):
shard_map's replicated-param cotangent rules assemble the global decoded
gradient with no explicit reduction. Everything pinned against the
single-device oracle in tests/test_ring.py.
"""

from __future__ import annotations

from functools import partial

import jax
from erasurehead_tpu.utils import compat
import jax.numpy as jnp
from jax import lax

from erasurehead_tpu.models.glm import MarginClassifierBase
from erasurehead_tpu.ops.features import FieldOnehot, PaddedRows
from erasurehead_tpu.parallel.ring import (
    reference_attention,
    ring_attention_shard,
    ulysses_attention_shard,
)


class AttentionModel(MarginClassifierBase):
    name = "attention"

    def __init__(
        self,
        d_in: int = 8,
        d_model: int = 16,
        n_heads: int = 2,
        seq_axis: str | None = None,
        sp_form: str = "ring",
    ):
        if d_model % n_heads:
            raise ValueError(f"{d_model=} must be divisible by {n_heads=}")
        if sp_form not in ("ring", "ulysses"):
            raise ValueError(f"sp_form must be ring/ulysses, got {sp_form!r}")
        self.d_in = d_in
        self.d_model = d_model
        self.n_heads = n_heads
        # when set, predict/grad_sum must run inside a shard_map whose mesh
        # carries this axis (the trainer's for_mesh hook arranges it)
        self.seq_axis = seq_axis
        self.sp_form = sp_form

    def for_mesh(self, mesh):
        """Trainer hook: a sequence-parallel copy when the mesh has a seq
        axis, self otherwise (train/trainer.py applies this to the model
        used for step construction only — eval replay stays unsharded)."""
        from erasurehead_tpu.parallel.mesh import axis_active
        from erasurehead_tpu.parallel.ring import SEQ_AXIS

        if axis_active(mesh, SEQ_AXIS):
            return AttentionModel(
                self.d_in, self.d_model, self.n_heads,
                seq_axis=SEQ_AXIS, sp_form=self.sp_form,
            )
        return self

    def _heads(self, x):
        """[..., m] -> [..., H, m/H] per-head split (concat-projection
        convention: wq/wk/wv stay [m, m]; heads are views)."""
        H = self.n_heads
        return x.reshape(*x.shape[:-1], H, self.d_model // H)

    def _merge(self, x):
        return x.reshape(*x.shape[:-2], self.d_model)

    def init_params(self, key: jax.Array, n_features: int):
        if n_features % self.d_in:
            raise ValueError(
                f"n_features={n_features} must be divisible by d_in={self.d_in} "
                f"(rows reshape to [T, {self.d_in}] token sequences)"
            )
        ks = jax.random.split(key, 5)
        d, m = self.d_in, self.d_model
        s_in = 1.0 / jnp.sqrt(d)
        s_m = 1.0 / jnp.sqrt(m)
        return {
            "embed": s_in * jax.random.normal(ks[0], (d, m)),
            "wq": s_m * jax.random.normal(ks[1], (m, m)),
            "wk": s_m * jax.random.normal(ks[2], (m, m)),
            "wv": s_m * jax.random.normal(ks[3], (m, m)),
            "w_out": s_m * jax.random.normal(ks[4], (m,)),
            "b_out": jnp.zeros(()),
        }

    def predict(self, params, X):
        if isinstance(X, (PaddedRows, FieldOnehot)):
            raise TypeError(
                "the attention model requires dense features (rows reshape "
                "to token sequences); sparse data is not supported"
            )
        Xd = jnp.asarray(X).astype(jnp.float32)
        n, F = Xd.shape
        T = F // self.d_in
        tokens = Xd.reshape(n, T, self.d_in)
        if self.seq_axis is not None:
            return self._predict_seq(params, tokens, T)
        h = tokens @ params["embed"]  # [n, T, m]

        def attend(hseq):
            q = self._heads(hseq @ params["wq"])  # [T, H, dh]
            k = self._heads(hseq @ params["wk"])
            v = self._heads(hseq @ params["wv"])
            per_head = jax.vmap(reference_attention, in_axes=1, out_axes=1)
            return self._merge(per_head(q, k, v))

        a = jax.vmap(attend)(h)  # [n, T, m]
        pooled = (h + a).mean(axis=1)  # residual + mean pool, [n, m]
        return pooled @ params["w_out"] + params["b_out"]

    def _predict_seq(self, params, tokens, T):
        """Sequence-parallel forward: this seq member embeds and projects
        only its token slice; ring attention supplies the full-sequence
        context; the pooled activations psum over the axis (identical
        margins on every member)."""
        ax = self.seq_axis
        s = compat.axis_size(ax)
        if T % s:
            raise ValueError(
                f"T={T} tokens must divide over {s} sequence shards"
            )
        Tl = T // s
        i = lax.axis_index(ax)
        tok_l = lax.dynamic_slice_in_dim(tokens, i * Tl, Tl, axis=1)
        h_l = tok_l @ params["embed"]  # [n, Tl, m]
        q = self._heads(h_l @ params["wq"])  # [n, Tl, H, dh]
        k = self._heads(h_l @ params["wk"])
        v = self._heads(h_l @ params["wv"])
        if self.sp_form == "ulysses":
            # one all_to_all to head-sharded full sequences and back
            # (ulysses_attention_shard validates n_heads % axis_size)
            a_l = jax.vmap(
                partial(ulysses_attention_shard, axis_name=ax)
            )(q, k, v)  # [n, Tl, H, dh]
        else:
            a_l = jax.vmap(
                jax.vmap(
                    partial(ring_attention_shard, axis_name=ax),
                    in_axes=1, out_axes=1,  # per-row [Tl, H, dh]: head axis
                )
            )(q, k, v)  # rows x heads around the ring
        pooled = lax.psum(
            (h_l + self._merge(a_l)).sum(axis=1), ax
        ) / T  # [n, m]
        return pooled @ params["w_out"] + params["b_out"]

    # loss_sum stays the PLAIN unscaled sum (MarginClassifierBase): the
    # sharded step differentiates it directly (step._weighted_loss_grad)
    # and shard_map's vma rules alone produce exact gradients — invariant
    # head-param cotangents need no reduction, seq-varying embed/qkv
    # cotangents get the implicit replicated-param psum.

    def grad_sum(self, params, X, y):
        """Plain gradient (host/oracle use). Standalone inside a seq-axis
        shard_map the recipe is scale-the-loss-by-1/axis_size then psum:
        replicated-path leaves (head) arrive full-per-member and the psum
        undoes the scaling; partitioned-path leaves (embed/qkv) arrive as
        member slices and the psum assembles them — pinned against the
        unsharded oracle in tests/test_ring.py."""
        if self.seq_axis is None:
            return jax.grad(self.loss_sum)(params, X, y)
        ax = self.seq_axis
        scaled = lambda p: self.loss_sum(p, X, y) / compat.axis_size(ax)
        return lax.psum(jax.grad(scaled)(params), ax)

    grad_sum_auto = grad_sum
