"""Two-layer MLP: the stretch model family (BASELINE.json configs[4]).

The reference trains only convex GLMs; this model exists to show the coded-DP
machinery is model-agnostic: parameters are a pytree, per-partition gradients
come from jax.grad of the summed loss, and the coding/decode layer combines
gradient *pytrees* with the same weights it uses for GLM gradient vectors.

Architecture: margins = tanh(X W1 + b1) @ w2 + b2, binary labels in {-1, +1},
logistic loss on the margin — so it drops into the same training/eval harness
(loss curves, AUC) as logistic regression.

``tp_axis`` composes tensor parallelism with the coded DP on a 2-D
(workers, model) mesh (parallel/mesh.worker_tp_mesh, ``--tp-shards``): the
Megatron split for a 2-layer block — W1 column-sharded, the tanh applied
per local hidden slice (elementwise, so the split is exact), w2
row-sharded, partial margins psum'd over the model axis — margins
identical on every member. Gradients under the coded step come from ONE
jax.grad of the weighted scalar loss per device (step._weighted_loss_grad);
shard_map's replicated-param cotangent rules assemble exact global
gradients for the sliced and replicated paths alike, the same mechanics
the attention family's seq mode uses.
"""

from __future__ import annotations

import jax
from erasurehead_tpu.utils import compat
import jax.numpy as jnp
from jax import lax

from erasurehead_tpu.models.glm import MarginClassifierBase
from erasurehead_tpu.ops.features import matvec


class MLPModel(MarginClassifierBase):
    name = "mlp"

    def __init__(self, hidden: int = 64, tp_axis: str | None = None):
        self.hidden = hidden
        # when set, predict must run inside a shard_map whose mesh carries
        # this axis (the trainer's for_mesh hook arranges it)
        self.tp_axis = tp_axis

    def for_mesh(self, mesh):
        """Trainer hook: a tensor-parallel copy when the mesh has a model
        axis, self otherwise (scoped to step construction — eval replay
        stays unsharded)."""
        from erasurehead_tpu.parallel.mesh import MODEL_AXIS, axis_active

        if axis_active(mesh, MODEL_AXIS):
            return MLPModel(self.hidden, tp_axis=MODEL_AXIS)
        return self

    def init_params(self, key: jax.Array, n_features: int):
        k1, k2 = jax.random.split(key)
        scale = 1.0 / jnp.sqrt(n_features)
        return {
            "W1": scale * jax.random.normal(k1, (n_features, self.hidden)),
            "b1": jnp.zeros(self.hidden),
            "w2": jax.random.normal(k2, (self.hidden,)) / jnp.sqrt(self.hidden),
            "b2": jnp.zeros(()),
        }

    def predict(self, params, X):
        if self.tp_axis is not None:
            return self._predict_tp(params, X)
        h = jnp.tanh(matvec(X, params["W1"]) + params["b1"])
        return matvec(h, params["w2"]) + params["b2"]

    def _predict_tp(self, params, X):
        """Tensor-parallel forward: this member computes its hidden slice
        only; partial margins psum over the model axis."""
        ax = self.tp_axis
        p = compat.axis_size(ax)
        H = params["b1"].shape[0]
        if H % p:
            raise ValueError(f"hidden={H} must divide over {p} tp shards")
        Hl = H // p
        i = lax.axis_index(ax)
        W1_l = lax.dynamic_slice_in_dim(params["W1"], i * Hl, Hl, axis=1)
        b1_l = lax.dynamic_slice_in_dim(params["b1"], i * Hl, Hl, axis=0)
        w2_l = lax.dynamic_slice_in_dim(params["w2"], i * Hl, Hl, axis=0)
        h_l = jnp.tanh(matvec(X, W1_l) + b1_l)  # [n, H/p]
        return lax.psum(matvec(h_l, w2_l), ax) + params["b2"]

