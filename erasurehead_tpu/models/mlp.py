"""Two-layer MLP: the stretch model family (BASELINE.json configs[4]).

The reference trains only convex GLMs; this model exists to show the coded-DP
machinery is model-agnostic: parameters are a pytree, per-partition gradients
come from jax.grad of the summed loss, and the coding/decode layer combines
gradient *pytrees* with the same weights it uses for GLM gradient vectors.

Architecture: margins = tanh(X W1 + b1) @ w2 + b2, binary labels in {-1, +1},
logistic loss on the margin — so it drops into the same training/eval harness
(loss curves, AUC) as logistic regression.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from erasurehead_tpu.models.glm import MarginClassifierBase
from erasurehead_tpu.ops.features import matvec


class MLPModel(MarginClassifierBase):
    name = "mlp"

    def __init__(self, hidden: int = 64):
        self.hidden = hidden

    def init_params(self, key: jax.Array, n_features: int):
        k1, k2 = jax.random.split(key)
        scale = 1.0 / jnp.sqrt(n_features)
        return {
            "W1": scale * jax.random.normal(k1, (n_features, self.hidden)),
            "b1": jnp.zeros(self.hidden),
            "w2": jax.random.normal(k2, (self.hidden,)) / jnp.sqrt(self.hidden),
            "b2": jnp.zeros(()),
        }

    def predict(self, params, X):
        h = jnp.tanh(matvec(X, params["W1"]) + params["b1"])
        return matvec(h, params["w2"]) + params["b2"]

