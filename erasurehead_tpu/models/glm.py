"""Convex GLM models: logistic regression and least-squares linear regression.

These are the two model families of the reference (SURVEY.md §2.2): a single
dense parameter vector ``beta`` trained by (accelerated) gradient descent on
row-sharded data. Gradients follow the reference's *sum* (not mean) convention
— the master applies ``lr/n_samples`` at update time (src/naive.py:113-115) —
so per-partition gradients add linearly, which is what makes gradient coding's
"message = linear combination of partition gradients" work.

Closed forms being matched (citations into /root/reference):
  - logistic gradient  -X^T (y / (exp((X beta) * y) + 1)):
    src/naive.py:137-139, src/approximate_coding.py:194-196
  - linear (least-squares) gradient  -2 X^T (y - X beta):
    src/naive.py:341-346, src/approximate_coding.py:333
  - logistic loss  mean log(1 + exp(-y * pred)): src/util.py:136-137
  - mse loss: src/util.py:139-141

Each model also exposes ``grad_sum_auto`` (jax.grad of the summed loss) — the
extensible path that the MLP and any future model family shares; tests pin the
closed forms to it.
"""

from __future__ import annotations

from typing import Any, Protocol

import jax
import jax.numpy as jnp

from erasurehead_tpu.ops.features import matvec, rmatvec

Params = Any  # pytree


class Model(Protocol):
    """Model interface used by the coded trainer.

    ``grad_sum`` must be additive over row-disjoint data shards:
    grad_sum(p, concat(X1, X2), concat(y1, y2)) ==
    grad_sum(p, X1, y1) + grad_sum(p, X2, y2). All the coding theory rests on
    this.
    """

    def init_params(self, key: jax.Array, n_features: int) -> Params: ...

    def predict(self, params: Params, X) -> jnp.ndarray: ...

    def grad_sum(self, params: Params, X, y) -> Params: ...

    def loss_sum(self, params: Params, X, y) -> jnp.ndarray: ...

    def loss_mean(self, params: Params, X, y) -> jnp.ndarray: ...


class MarginClassifierBase:
    """Shared logistic-margin loss machinery for non-GLM classifier
    families (MLP, attention): softplus loss on ``predict``'s margin and
    jax.grad gradients. One home so the loss definition cannot diverge
    across model families.

    ``grads_via_loss``: under the sharded step these models' gradients are
    taken as ONE jax.grad of the weighted scalar loss per device — jax.grad
    w.r.t. replicated params inside shard_map implicitly psums cotangents
    across the mesh, so per-slot grad calls there would double-count (see
    parallel/step._grads_via_loss). ``grad_sum`` itself remains the plain
    unsharded gradient for host/oracle use."""

    grads_via_loss = True

    def loss_sum(self, params, X, y):
        return jnp.sum(jax.nn.softplus(-y * self.predict(params, X)))

    def loss_mean(self, params, X, y):
        return self.loss_sum(params, X, y) / y.shape[0]

    def grad_sum(self, params, X, y):
        return jax.grad(self.loss_sum)(params, X, y)

    grad_sum_auto = grad_sum


class _GLMBase:
    def init_params(self, key: jax.Array, n_features: int) -> jnp.ndarray:
        """Standard-normal init.

        The reference initializes beta ~ randn with no seed in naive/
        replication/approx (src/naive.py:23) but zeros in coded/avoidstragg
        (src/coded.py:52) — so its cross-scheme loss curves start from
        different points (SURVEY.md §2.5). We deliberately use one seeded
        init everywhere so scheme comparisons are paired.
        """
        return jax.random.normal(key, (n_features,))

    def predict(self, params, X):
        return matvec(X, params)

    def grad_sum_auto(self, params, X, y):
        return jax.grad(self.loss_sum)(params, X, y)

    def loss_mean(self, params, X, y):
        return self.loss_sum(params, X, y) / y.shape[0]


class LogisticModel(_GLMBase):
    """Binary logistic regression with labels in {-1, +1}."""

    name = "logistic"

    def margin_residual(self, margins, y):
        """r such that grad_sum = -X^T r. Elementwise in the row, which is
        what lets the flat-stack grad lowering (parallel/step.
        make_flat_grad_fn) fold per-slot decode weights into a per-row
        scale of r."""
        # written the reference's way: y / (exp(m*y) + 1)  (src/naive.py:137-139)
        return y / (jnp.exp(margins * y) + 1.0)

    def grad_sum(self, params, X, y):
        margins = matvec(X, params)
        # d/dbeta sum_r log(1+exp(-y_r m_r)) = -X^T (y * sigmoid(-y*m))
        r = self.margin_residual(margins, y)
        return -rmatvec(X, r)

    def loss_sum(self, params, X, y):
        margins = matvec(X, params)
        # log(1+exp(-z)) via softplus for numerical stability; the reference's
        # literal form (src/util.py:136-137) overflows for large negative
        # margins.
        return jnp.sum(jax.nn.softplus(-y * margins))


class LinearModel(_GLMBase):
    """Least-squares linear regression (kc_house_data task)."""

    name = "linear"

    def margin_residual(self, margins, y):
        """r such that grad_sum = -X^T r (see LogisticModel.margin_residual):
        -2 X^T (y - X beta)  (src/naive.py:341-346)."""
        return 2.0 * (y - margins)

    def grad_sum(self, params, X, y):
        r = self.margin_residual(matvec(X, params), y)
        return -rmatvec(X, r)

    def loss_sum(self, params, X, y):
        resid = y - matvec(X, params)
        return jnp.sum(resid**2)

    def loss_mean(self, params, X, y):
        # reference eval uses sklearn mean_squared_error (src/util.py:139-141)
        return self.loss_sum(params, X, y) / y.shape[0]
