"""Deep MLP: the pipeline-parallel stretch family.

The reference trains convex GLMs only (SURVEY.md §2.2); the 2-layer MLP
showed pytree-params models ride the coded-DP machinery unchanged, and the
attention/MLP families composed SP and TP with it. This family supplies the
remaining classic axis: **pipeline parallelism**. ``n_layers`` uniform tanh
layers (input projection F→H, then L hidden H→H transforms, then a linear
head) split contiguously across a ``pipe`` mesh axis; a GPipe-style
microbatch schedule streams M microbatches through the stages under ONE
``lax.scan`` — at step t, stage i holds the activations of microbatch
t−i, ``lax.ppermute`` hands each stage's output to its successor, stage 0
injects microbatch t, and the last stage emits margins which psum-gather
to every member (so the loss is pipe-invariant). Gradients under the coded
step come from one jax.grad of the weighted scalar loss per device
(parallel/step._weighted_loss_grad): AD runs the pipeline in reverse
through the transposed ppermutes, and shard_map's replicated-param
cotangent rules assemble exact global gradients — pinned against the
unsharded oracle in tests (same method as the seq/TP modes).

Like those modes, this is compute/activation pipelining with replicated
parameters (each member holds the full stack but applies only its stage's
layers): the composition and schedule are real; param/optimizer-state
sharding is out of scope for this framework's model sizes.
"""

from __future__ import annotations

import jax
from erasurehead_tpu.utils import compat
import jax.numpy as jnp
from jax import lax

from erasurehead_tpu.models.glm import MarginClassifierBase
from erasurehead_tpu.ops.features import matvec

PIPE_AXIS = "pipe"


class DeepMLPModel(MarginClassifierBase):
    name = "deepmlp"
    # per-layer gradient coding (ops/blocks.py): the stacked [L, H, H]
    # hidden transforms and their biases split along the layer axis, so
    # each hidden layer's gradient is its own coded block — decode cost
    # stays one small einsum per block as n_layers grows
    block_split_leaves = ("W", "b")

    def __init__(
        self,
        hidden: int = 32,
        n_layers: int = 4,
        microbatches: int = 0,  # 0 => pipe axis size (one per stage)
        pp_axis: str | None = None,
    ):
        self.hidden = hidden
        self.n_layers = n_layers
        self.microbatches = microbatches
        # when set, predict must run inside a shard_map whose mesh carries
        # this axis (the trainer's for_mesh hook arranges it)
        self.pp_axis = pp_axis

    def for_mesh(self, mesh):
        """Trainer hook: a pipeline-parallel copy when the mesh has a pipe
        axis (scoped to step construction; eval replay stays unsharded)."""
        from erasurehead_tpu.parallel.mesh import axis_active

        if axis_active(mesh, PIPE_AXIS):
            return DeepMLPModel(
                self.hidden, self.n_layers, self.microbatches,
                pp_axis=PIPE_AXIS,
            )
        return self

    def init_params(self, key: jax.Array, n_features: int):
        ks = jax.random.split(key, 3)
        H, L = self.hidden, self.n_layers
        return {
            "W_in": jax.random.normal(ks[0], (n_features, H))
            / jnp.sqrt(n_features),
            "b_in": jnp.zeros(H),
            # the L hidden transforms, stacked [L, H, H] so a stage can
            # dynamic-slice its contiguous block
            "W": jax.random.normal(ks[1], (L, H, H)) / jnp.sqrt(H),
            "b": jnp.zeros((L, H)),
            "w_out": jax.random.normal(ks[2], (H,)) / jnp.sqrt(H),
            "b_out": jnp.zeros(()),
        }

    def _apply_layers(self, params, h, lo, count):
        """tanh hidden transforms lo..lo+count-1 (count static)."""
        for j in range(count):
            W = lax.dynamic_index_in_dim(params["W"], lo + j, keepdims=False)
            b = lax.dynamic_index_in_dim(params["b"], lo + j, keepdims=False)
            h = jnp.tanh(h @ W + b)
        return h

    def _embed(self, params, X):
        """Input projection through ops/features.matvec so dense ndarray,
        PaddedRows, and FieldOnehot inputs all work (only this layer
        touches X; everything after is dense-on-dense)."""
        return jnp.tanh(matvec(X, params["W_in"]) + params["b_in"])

    def predict(self, params, X):
        if self.pp_axis is not None:
            return self._predict_pp(params, X)
        h = self._apply_layers(params, self._embed(params, X), 0, self.n_layers)
        return h @ params["w_out"] + params["b_out"]

    def _predict_pp(self, params, X):
        """GPipe-schedule forward over the pipe axis (module docstring).

        The input projection runs up front on the full local batch (every
        member computes it — replicated stage-0 preamble, which also keeps
        sparse feature containers out of the microbatch indexing); the
        pipeline streams its dense [mb, H] activations."""
        ax = self.pp_axis
        p = compat.axis_size(ax)
        i = lax.axis_index(ax)
        L = self.n_layers
        if L % p:
            raise ValueError(f"n_layers={L} must divide over {p} pp stages")
        per_stage = L // p
        n = X.shape[0]
        M = self.microbatches or p
        if n % M:
            raise ValueError(
                f"{n} rows must divide into {M} pipeline microbatches"
            )
        mb = n // M
        H = self.hidden
        Hmb = self._embed(params, X).reshape(M, mb, H)
        perm = [(s, s + 1) for s in range(p - 1)]  # stage s -> s+1

        def stage_fn(x_in):
            return self._apply_layers(params, x_in, i * per_stage, per_stage)

        def step(carry, t):
            act, out = carry
            # hand the previous step's activations to the next stage;
            # stage 0 has no predecessor and ppermute leaves zeros there
            received = lax.ppermute(act, ax, perm)
            # stage 0 injects microbatch t (zeros once the input drains)
            inject = jnp.where(
                t < M, Hmb[jnp.minimum(t, M - 1)], jnp.zeros((mb, H))
            )
            x_in = jnp.where(i == 0, inject, received)
            act_new = stage_fn(x_in)
            # microbatch t-(p-1) exits the last stage at step t
            m_out = act_new @ params["w_out"] + params["b_out"]  # [mb]
            slot = t - (p - 1)
            valid = jnp.logical_and(slot >= 0, i == p - 1)
            out = lax.dynamic_update_index_in_dim(
                out,
                jnp.where(valid, m_out, out[jnp.maximum(slot, 0)]),
                jnp.maximum(slot, 0),
                axis=0,
            )
            return (act_new, out), None

        # initial carries: zeros that must carry BOTH the data's varying
        # axes (inherited by deriving from the embedded batch — workers
        # under the trainer) AND the pipe axis (explicit pcast: every
        # later carry depends on axis_index), keeping the scan carry type
        # stable under vma checking
        act0 = compat.pcast(Hmb[0] * 0.0, ax, to="varying")
        out0 = jnp.zeros((M, mb)) + act0[:, 0] * 0.0
        (_, out), _ = lax.scan(
            step, (act0, out0), jnp.arange(M + p - 1)
        )
        # margins live on the last stage; gather them to every member so
        # the loss is identical (pipe-invariant) everywhere
        margins = lax.psum(jnp.where(i == p - 1, out, 0.0), ax)
        return margins.reshape(n)
