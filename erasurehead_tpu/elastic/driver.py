"""train_elastic_online: telemetry-driven elastic membership over the
chunked-restart seam.

``parallel/failures.train_elastic`` generalizes to N restarts in either
direction, with nothing scripted: training runs in chunks through the
``initial_state``/``initial_round`` contract (train/trainer.py — the same
seam adapt/driver.py uses), and between chunks the
:class:`~erasurehead_tpu.elastic.controller.MembershipController` reads
the chunk's OWN arrival telemetry to decide membership:

  - a worker whose ``-1`` never-arrived sentinel persists (or whose
    ``detect_dead`` timeout trips) for K consecutive rounds is declared
    dead → at the next chunk boundary the run re-layouts onto the
    survivors: a fresh code matrix for W' via the scheme registry's
    layout builders (schemes/base.py descriptors bundle them), params +
    momentum carried over, the resolved lr schedule continuous;
  - a collapsed arrival regime (the adapt/ shift rule) triggers a
    corroborated re-evaluation (a "probe");
  - a join offer (chaos ``worker_revive``, a scripted revive, a widened
    mesh) scales the layout back UP the same way.

Chunks run under ``failures.plan_run(on_infeasible="failover",
timeout=...)``: a not-yet-detected dead worker costs failover rounds at
the master's ``timeout`` patience instead of the reference's hang-forever
(README.md:120-122) — which is exactly the cost signal that makes
detection pay for itself, and what the bench ``elastic`` extra's
keep-limping baseline keeps paying for the whole horizon.

Every decision and every finished chunk is a typed ``membership`` event
(obs/events.SCHEMA): decisions journal what the controller did, and
``action="chunk"`` rows carry the chunk's science (sim clock, decode
error, params digest). The whole run is deterministic given (config,
world, chaos env) — chaos-armed kills index membership firings by
ABSOLUTE chunk boundary (utils/chaos.membership_fires), detection is
threshold-based, and the adapt bandit (when composed) re-seeds per epoch
— so a killed run REPLAYS: resumed from the checkpoint+aux sidecar, the
completed chunks' rows rehydrate bitwise from the journal and the rest
recompute identically (test-pinned).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Mapping, Optional, Sequence

import numpy as np

from erasurehead_tpu.elastic.controller import (
    ElasticConfig,
    MembershipController,
    auto_survivor_config,
    default_join_offers,
)

#: journal file name inside the journal directory
JOURNAL_NAME = "elastic_journal.jsonl"

#: envelope fields excluded from the bitwise row-rehydration contract
#: (they are properties of the writing process, not of the science)
ROW_VOLATILE = ("seq", "t")


def science_fields(rec: Mapping) -> dict:
    """A journal record minus the per-process envelope — the part the
    kill→resume bitwise invariance covers."""
    return {k: v for k, v in rec.items() if k not in ROW_VOLATILE}


@dataclasses.dataclass
class ElasticResult:
    """A merged TrainResult plus the membership decision record."""

    result: Any  # trainer.TrainResult over the full horizon
    #: controller decisions (death/join/relayout/probe dicts, in order)
    decisions: list
    #: one dict per layout epoch: start round, worker set, chosen s
    epochs: list
    #: per-chunk science rows (action="chunk" journal payloads, round
    #: order; on a resumed run the pre-resume prefix is REHYDRATED from
    #: the journal, not recomputed)
    rows: list
    #: adapt-bandit decisions across all epochs ([] without adapt_arms)
    arm_decisions: list
    journal_path: Optional[str]
    #: first round actually trained by THIS process (resume), else 0
    resumed_from: int


def _digest_tree(tree) -> str:
    """Deterministic content digest of a pytree of arrays (host fetch is
    multihost-safe via sharding.np_global)."""
    import jax

    from erasurehead_tpu.data import sharding as sharding_lib

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(tree):
        arr = np.ascontiguousarray(sharding_lib.np_global(leaf))
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
    return h.hexdigest()[:16]


def _emit(logger, type_: str, **fields) -> None:
    """Emit into the driver's own journal (when open) AND the ambient
    telemetry capture (when installed)."""
    from erasurehead_tpu.obs import events as obs_events

    if logger is not None:
        logger.emit(type_, **fields)
    obs_events.emit(type_, **fields)


def _apply_scripted(avail: np.ndarray, deaths, revives, W: int) -> None:
    """Scripted ground-truth availability: per-worker death/revive events
    applied in round order (a revive after a death re-opens the column)."""
    R = avail.shape[0]
    events: dict[int, list] = {}
    for w, r in (deaths or {}).items():
        w, r = int(w), int(r)
        if not 0 <= w < W:
            raise ValueError(f"scripted death for worker {w} outside [0, {W})")
        events.setdefault(w, []).append((r, False))
    for w, r in (revives or {}).items():
        w, r = int(w), int(r)
        if not 0 <= w < W:
            raise ValueError(
                f"scripted revive for worker {w} outside [0, {W})"
            )
        events.setdefault(w, []).append((r, True))
    for w, evs in events.items():
        for r, alive in sorted(evs):
            avail[max(r, 0):R, w] = alive


def _filter_arms(cfg_epoch, arms) -> list:
    """The registry-compatible subset of ``arms`` for this epoch's config:
    each arm must validate as a config AND build the same device data
    stack (adapt/driver._validate_arms — the weight-table-only switch
    contract). The epoch's own policy is always arm 0, so the bandit can
    never be left armless by a W' that invalidates every alternative."""
    from erasurehead_tpu.adapt.controller import Arm
    from erasurehead_tpu.adapt.driver import _validate_arms

    base = Arm(
        cfg_epoch.scheme.value, cfg_epoch.num_collect, cfg_epoch.deadline
    )
    out = [base]
    for arm in arms or ():
        if arm.label == base.label:
            continue
        try:
            _validate_arms(cfg_epoch, [arm])
        except ValueError:
            continue
        out.append(arm)
    return out


def _load_journal_rows(path: str) -> dict[int, dict]:
    """round -> science row for every ``action="chunk"`` membership record
    in the journal (last record per round wins — a chunk re-run after a
    kill-between-row-and-checkpoint appends an identical duplicate)."""
    rows: dict[int, dict] = {}
    if not os.path.exists(path):
        return rows
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # one torn final line after a kill is expected
            if (
                isinstance(rec, dict)
                and rec.get("type") == "membership"
                and rec.get("action") == "chunk"
                and isinstance(rec.get("round"), int)
            ):
                rows[rec["round"]] = science_fields(rec)
    return rows


def train_elastic_online(
    cfg,
    dataset,
    *,
    elastic: Optional[ElasticConfig] = None,
    mesh=None,
    arrivals: Optional[np.ndarray] = None,
    deaths: Optional[Mapping[int, int]] = None,
    revives: Optional[Mapping[int, int]] = None,
    survivor_overrides: Optional[dict] = None,
    adapt_arms: Optional[Sequence] = None,
    journal_dir: Optional[str] = None,
    checkpoint_dir: Optional[str] = None,
    resume: bool = False,
) -> ElasticResult:
    """Train ``cfg.rounds`` rounds with ONLINE membership (module
    docstring).

    ``deaths``/``revives`` script the ground-truth world (``{worker:
    round}`` — what actually happens to the cluster); the controller only
    ever sees the resulting telemetry. Chaos ``worker_death``/
    ``worker_revive`` specs (utils/chaos.py) mutate the same world at
    chunk boundaries. ``adapt_arms`` composes the adapt/ bandit: within
    each membership epoch it re-chooses the collection policy per chunk
    over the arms compatible with that epoch's layout-stack signature
    (fresh, re-seeded controller per epoch). ``journal_dir`` appends the
    typed membership/row stream to ``elastic_journal.jsonl``;
    ``checkpoint_dir`` + ``resume=True`` restart from the latest
    checkpoint with the controller ledger restored from its aux sidecar.
    """
    import jax

    from erasurehead_tpu.adapt.controller import (
        AdaptiveController,
        ChunkStats,
        ControllerConfig,
    )
    from erasurehead_tpu.data import sharding as sharding_lib
    from erasurehead_tpu.obs import events as obs_events
    from erasurehead_tpu.parallel import failures
    from erasurehead_tpu.train import checkpoint as ckpt_lib
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils import chaos as chaos_lib

    ecfg = elastic or ElasticConfig()
    if cfg.arrival_mode != "simulated":
        raise ValueError(
            "train_elastic_online drives the scan trainer in chunks; "
            "arrival_mode='measured' has no chunked implementation"
        )
    from erasurehead_tpu import schemes

    if schemes.get(cfg.scheme).partial:
        raise ValueError(
            f"scheme {cfg.scheme.value!r}: partial two-part layouts "
            "structurally require every worker's uncoded first-part — "
            "neither failover rounds nor a W' re-layout exist for them"
        )
    if resume and not checkpoint_dir:
        raise ValueError("resume=True requires checkpoint_dir")

    R, W = cfg.rounds, cfg.n_workers
    base_arr = np.asarray(
        arrivals if arrivals is not None else trainer.default_arrivals(cfg),
        dtype=np.float64,
    )
    if base_arr.shape != (R, W):
        raise ValueError(
            f"arrivals shape {base_arr.shape} != ({R}, {W})"
        )
    avail = np.ones((R, W), dtype=bool)
    _apply_scripted(avail, deaths, revives, W)
    lr_full = cfg.resolve_lr_schedule()
    chunk = ecfg.chunk_rounds

    def boundary_index(lo: int) -> int:
        return lo // chunk + 1

    def apply_boundary_chaos(lo: int) -> list[int]:
        """Mutate the world per the chaos membership specs firing at this
        boundary; returns the revive offers. Indexed by ABSOLUTE boundary
        so resumed runs replay rather than re-fire."""
        b = boundary_index(lo)
        for w in chaos_lib.membership_fires("worker_death", b):
            if not 0 <= w < W:
                raise ValueError(
                    f"chaos worker_death id {w} outside [0, {W})"
                )
            avail[lo:, w] = False
        offers = []
        for w in chaos_lib.membership_fires("worker_revive", b):
            if not 0 <= w < W:
                raise ValueError(
                    f"chaos worker_revive id {w} outside [0, {W})"
                )
            avail[lo:, w] = True
            offers.append(int(w))
        return offers

    # ---- journal + resume state -------------------------------------------
    journal_path = None
    logger = None
    if journal_dir:
        journal_path = os.path.join(journal_dir, JOURNAL_NAME)
        logger = obs_events.EventLogger(journal_path, mode="a")

    mem = MembershipController(W, ecfg)
    state = None
    start_round = 0
    bandit_state = None
    timeset = np.zeros(R)
    wt = np.full((R, W), -1.0)
    col = np.zeros((R, W), dtype=bool)
    derr = np.zeros(R)
    rows: list[dict] = []
    n_train_min: Optional[int] = None

    if resume:
        template = trainer._init_params_f32(
            cfg, trainer.build_model(cfg), dataset.n_features
        )
        from erasurehead_tpu.train.optimizer import init_state

        restored = ckpt_lib.restore_latest_with_aux(
            checkpoint_dir, init_state(template, cfg.update_rule)
        )
        if restored is not None:
            state, start_round, _path, aux = restored
            mem = MembershipController.restore(aux["controller"], ecfg)
            bandit_state = aux.get("bandit")
            n_train_min = aux.get("n_train_min")
            timeset[:start_round] = np.asarray(
                aux["timeset"], dtype=np.float64
            )
            wt[:start_round] = np.asarray(aux["wt"], dtype=np.float64)
            col[:start_round] = np.asarray(aux["col"], dtype=bool)
            derr[:start_round] = np.asarray(aux["derr"], dtype=np.float64)
            # replay past boundaries' chaos against the world (no
            # controller calls: its state came from the aux ledger)
            lo_replay = 0
            while lo_replay < start_round:
                apply_boundary_chaos(lo_replay)
                lo_replay = min(lo_replay + chunk, R)
            # rows for completed chunks REHYDRATE from the journal —
            # bitwise, not recomputed (the acceptance contract)
            if journal_path:
                journaled = _load_journal_rows(journal_path)
                rows = [
                    journaled[r]
                    for r in sorted(journaled)
                    if r < start_round
                ]
            elif "rows" in aux:
                rows = list(aux["rows"])

    run_id = obs_events.new_run_id() if obs_events.current() else None
    pieces = []  # per-chunk params_history trees (host numpy)
    epochs: list[dict] = []
    arm_decisions: list[dict] = []
    last_res = None
    bandit = None
    bandit_epoch = -1
    arms_used: list = []
    train_wall = 0.0

    lo = start_round
    while lo < R:
        # chaos site "elastic": a kill here is a preemption at a chunk
        # boundary; the resumed run restores the ledger and replays
        chaos_lib.maybe_fire("elastic")
        offers = apply_boundary_chaos(lo)
        for w in offers:
            mem.request_join(w, round=lo)
        for w in default_join_offers(revives, mem.active, lo):
            mem.request_join(w, round=lo)
        change = mem.commit(lo)
        if change is not None:
            if change.dead:
                _emit(
                    logger, "membership", round=lo, action="death",
                    workers=list(change.dead),
                    n_workers=change.n_workers_after,
                )
            if change.joined:
                _emit(
                    logger, "membership", round=lo, action="join",
                    workers=list(change.joined),
                    n_workers=change.n_workers_after,
                )
            _emit(
                logger, "membership", round=lo, action="relayout",
                workers=list(mem.active),
                n_workers=change.n_workers_after, epoch=mem.epoch,
                n_workers_before=change.n_workers_before,
            )

        hi = min(lo + chunk, R)
        active = list(mem.active)
        Wp = len(active)
        # epoch config: registry-validated survivor config (auto-shrunk
        # n_stragglers where the scheme's divisibility demands it), the
        # resolved lr schedule staying continuous through every re-layout
        cfg_epoch = auto_survivor_config(cfg, Wp, survivor_overrides)
        if not epochs or epochs[-1]["workers"] != tuple(active):
            epochs.append({
                "start_round": lo,
                "epoch": mem.epoch,
                "workers": tuple(active),
                "n_workers": Wp,
                "n_stragglers": cfg_epoch.n_stragglers,
            })

        if adapt_arms is not None and bandit_epoch != mem.epoch:
            # arms re-seed against the new layout-stack signature: a
            # fresh, deterministically re-seeded bandit per epoch
            arms_used = _filter_arms(cfg_epoch, adapt_arms)
            bandit = AdaptiveController(
                arms_used,
                ControllerConfig(
                    chunk_rounds=chunk,
                    seed=ecfg.seed + mem.epoch,
                    reward_mode="time_error",
                ),
            )
            if bandit_state is not None:
                bandit.load_state_dict(bandit_state)
                bandit_state = None
            bandit_epoch = mem.epoch

        arm = None
        arm_idx = None
        cfg_chunk = dataclasses.replace(
            cfg_epoch, rounds=hi, lr_schedule=lr_full[:hi]
        )
        if bandit is not None:
            arm_idx, reason = bandit.choose()
            arm = arms_used[arm_idx]
            arm_decisions.append({**bandit.decisions[-1], "round": lo,
                                  "epoch": mem.epoch})
            cfg_chunk = dataclasses.replace(cfg_chunk, **arm.overrides())

        layout = trainer.build_layout(cfg_chunk)
        arr_e = base_arr[:hi][:, active].copy()
        arr_e[~avail[:hi][:, active]] = failures.DEAD
        schedule, _report = failures.plan_run(
            cfg_chunk.scheme, layout, arr_e,
            num_collect=cfg_chunk.num_collect,
            timeout=ecfg.timeout,
            on_infeasible="failover",
            deadline=cfg_chunk.deadline,
            decode=cfg_chunk.decode,
        )
        res = trainer.train(
            cfg_chunk, dataset, mesh=mesh, arrivals=arr_e,
            schedule=schedule,
            initial_state=state,
            initial_round=lo if state is not None else 0,
            measure=False,
        )
        state = res.final_state
        last_res = res
        train_wall += res.wall_time
        n_train_min = (
            res.n_train
            if n_train_min is None
            else min(n_train_min, res.n_train)
        )
        pieces.append(jax.tree.map(
            lambda leaf: sharding_lib.np_global(leaf), res.params_history
        ))
        timeset[lo:hi] = res.timeset[lo:hi]
        wt[lo:hi, active] = res.worker_times[lo:hi]
        col[lo:hi, active] = res.collected[lo:hi]
        derr[lo:hi] = res.decode_error[lo:hi]

        # the master's per-round listening window: the failover timeout,
        # capped by the deadline when the chunk ran a deadline rule —
        # rounds whose clock ran the window out are the evidential ones
        window = ecfg.timeout
        if cfg_chunk.deadline is not None:
            from erasurehead_tpu import schemes as schemes_lib

            if schemes_lib.get(cfg_chunk.scheme).needs_deadline:
                window = min(window, float(cfg_chunk.deadline))
        obs = mem.observe_chunk(
            lo, res.worker_times[lo:hi],
            sim_time=res.timeset[lo:hi], window=window,
        )
        if obs.collapse:
            _emit(
                logger, "membership", round=lo, action="probe",
                n_workers=Wp, arrival_mean=obs.arrival_mean,
            )
        if bandit is not None:
            raw = wt[lo:hi, active]
            arrived = raw[raw >= 0.0]
            stats = ChunkStats(
                n_rounds=hi - lo,
                sim_time=float(res.timeset[lo:hi].sum()),
                decode_error_mean=float(res.decode_error[lo:hi].mean()),
                arrival_mean=(
                    float(arrived.mean()) if arrived.size else None
                ),
                arrival_p90=(
                    float(np.quantile(arrived, 0.9))
                    if arrived.size
                    else None
                ),
            )
            bandit.observe(arm_idx, stats)

        row = dict(
            round=lo,
            action="chunk",
            n_rounds=hi - lo,
            n_workers=Wp,
            workers=list(active),
            epoch=mem.epoch,
            sim_time=float(res.timeset[lo:hi].sum()),
            decode_error_mean=float(res.decode_error[lo:hi].mean()),
            params_digest=_digest_tree(state.params),
            arm=arm.label if arm is not None else None,
            n_stragglers=cfg_chunk.n_stragglers,
        )
        _emit(logger, "membership", **row)
        rows.append(dict(type="membership", **row))

        if checkpoint_dir:
            aux = {
                "controller": mem.snapshot(),
                "bandit": (
                    bandit.state_dict() if bandit is not None else None
                ),
                "n_train_min": n_train_min,
                "timeset": timeset[:hi].tolist(),
                "wt": wt[:hi].tolist(),
                "col": col[:hi].tolist(),
                "derr": derr[:hi].tolist(),
                "rows": rows,
            }
            ckpt_lib.save_with_aux(
                os.path.join(checkpoint_dir, f"round_{hi}"), state, hi, aux
            )
        lo = hi

    if logger is not None:
        logger.close()
    if last_res is None:
        raise ValueError(
            f"nothing to train: resume start {start_round} >= rounds {R}"
        )

    history = (
        pieces[0]
        if len(pieces) == 1
        else jax.tree.map(
            lambda *xs: np.concatenate([np.asarray(x) for x in xs]),
            *pieces,
        )
    )
    merged = trainer.TrainResult(
        params_history=history,
        final_params=state.params,
        final_state=state,
        timeset=timeset,
        worker_times=wt,
        collected=col,
        sim_total_time=float(timeset.sum()),
        wall_time=train_wall,
        steps_per_sec=(
            (R - start_round) / train_wall if train_wall > 0 else 0.0
        ),
        n_train=n_train_min,
        start_round=start_round,
        config=cfg,
        layout=last_res.layout,
        decode_error=derr,
        run_id=run_id,
        cache_info=last_res.cache_info,
    )
    return ElasticResult(
        result=merged,
        decisions=list(mem.decisions),
        epochs=epochs,
        rows=rows,
        arm_decisions=arm_decisions,
        journal_path=journal_path,
        resumed_from=start_round,
    )
