"""Elastic membership: online death detection, mid-run re-layout, and
worker join — the wall the reference's README concedes (README.md:120-122,
any worker death hangs the master forever) taken down WITHOUT scripting
the deaths in advance.

- :mod:`erasurehead_tpu.elastic.controller` — the telemetry-driven
  membership detector (K-round ``-1``-sentinel streaks, detect_dead
  timeout trips, collapsed-arrival probes, join offers) and its
  deterministic ledger.
- :mod:`erasurehead_tpu.elastic.driver` — ``train_elastic_online``: the
  chunked restart loop that re-layouts onto W' via the scheme registry's
  layout builders, journals typed ``membership`` events, checkpoints the
  ledger, and composes with the adapt/ bandit, chaos harness, ring/int8
  stacks and deep models.
"""

from erasurehead_tpu.elastic.controller import (  # noqa: F401
    ChunkObservation,
    ElasticConfig,
    MembershipChange,
    MembershipController,
    auto_survivor_config,
)
from erasurehead_tpu.elastic.driver import (  # noqa: F401
    ElasticResult,
    science_fields,
    train_elastic_online,
)
