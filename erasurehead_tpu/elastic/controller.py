"""Online membership detection: who is still in the cluster, from the
run's own telemetry.

The framework's failure handling so far needed the deaths HANDED to it:
``parallel/failures.train_elastic`` re-shards only when the caller scripts
``{worker: round}`` in advance, and the adapt/ bandit switches collection
policy but never the worker count. This module closes the loop the
reference's README concedes is open (README.md:120-122 — any death hangs
its master forever): membership decisions are derived from what the run
itself observed, never from ground truth the master could not have.

Detection rules (ElasticConfig knobs, deterministic by construction):

  - **death (streak rule)** — a worker whose telemetry column carries the
    ``-1`` never-collected sentinel (or a ``detect_dead`` timeout trip,
    parallel/failures.py) for ``death_rounds`` CONSECUTIVE *evidential*
    rounds is declared dead. A round is evidential for worker w only when
    the master actually listened out its patience window (the round's
    sim clock reached ``min(timeout, deadline)``): under early-stopping
    policies (AGC's first-``num_collect`` rule, avoidstragg) the sentinel
    routinely marks workers the master simply STOPPED LISTENING for, and
    counting those as death evidence evicts healthy workers — measured at
    the canonical W=30 collect=15 config, an ungated K=3 streak rule
    declared 5 false deaths in 32 rounds. The streak must be consecutive:
    an in-patience arrival resets it to zero (the satellite test pins the
    all--1 vs transiently-slow distinction); a non-evidential round
    leaves it unchanged (absence of evidence is not evidence of life).
  - **death (absence rule)** — evidential rounds only exist while the
    death COSTS clock (failover/deadline rounds); a scheme with slack
    (AGC with ``alive >= num_collect``) keeps ending rounds early, so a
    dead worker there never produces one. The long-window backstop: a
    worker uncollected for ``absence_rounds`` consecutive rounds
    (default ``5 * death_rounds``) is declared dead regardless of
    evidence — a healthy worker under rotating early-stop policies is
    uncollected with probability well under 1 per round, so a long
    all-absent run is overwhelmingly a departure (or a worker so
    persistently slow that evicting it and re-sharding its partition is
    the right call anyway).
  - **collapse probe** — a chunk whose masked arrival mean jumps past
    ``shift_factor`` vs the previous chunk (the adapt/ shift detector's
    rule) triggers a membership re-evaluation: suspicion streaks of at
    least ``ceil(death_rounds / 2)`` are treated as corroborated and
    promoted to deaths — a collapsed arrival regime plus a persistent
    silent worker is evidence of the same event (a machine going away),
    and waiting the full K rounds just burns timeout-priced rounds.
  - **join** — an external offer (a chaos ``worker_revive``, a scripted
    revive, a widened mesh) queues a worker id; it enters the layout at
    the next commit. Joins are offers, not telemetry: a worker outside
    the layout produces none.

All decisions are recorded (``decisions``) and the full state snapshots to
JSON (:meth:`snapshot` / :meth:`restore`) so a killed-and-resumed elastic
run replays the identical decision sequence — the same determinism
contract the adapt/ controller carries.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Knobs of the online membership controller (+ its chunked driver)."""

    #: rounds per chunk — the restart/decision granularity (the
    #: initial_state/initial_round seam runs this many rounds at a time)
    chunk_rounds: int = 10
    #: K: consecutive never-arrived (or timed-out) rounds that declare a
    #: worker dead (the CLI's --death-rounds)
    death_rounds: int = 3
    #: per-round master patience in simulated seconds: arrivals beyond it
    #: are presumed dead for the round (failures.detect_dead) and failover
    #: stamps the round's clock at this value — must be finite, it is what
    #: keeps the master from inheriting the reference's hang-forever
    timeout: float = 5.0
    #: never shrink the layout below this many workers
    min_workers: int = 1
    #: arrival-mean jump factor (vs the previous chunk) that flags a
    #: collapsed regime and triggers the corroborated re-evaluation
    shift_factor: float = 2.5
    #: the long-window absence backstop (module docstring): a worker
    #: uncollected this many CONSECUTIVE rounds is dead even if no round
    #: was evidential. None = 5 * death_rounds.
    absence_rounds: Optional[int] = None
    #: seed for the composed adapt bandit (arms re-seed per epoch as
    #: seed + epoch); detection itself is threshold-based and seed-free
    seed: int = 0

    @property
    def effective_absence_rounds(self) -> int:
        return (
            self.absence_rounds
            if self.absence_rounds is not None
            else 5 * self.death_rounds
        )

    def __post_init__(self):
        if self.chunk_rounds < 1:
            raise ValueError(
                f"chunk_rounds must be >= 1, got {self.chunk_rounds}"
            )
        if self.death_rounds < 1:
            raise ValueError(
                f"death_rounds must be >= 1, got {self.death_rounds}"
            )
        if not np.isfinite(self.timeout) or self.timeout <= 0:
            raise ValueError(
                f"timeout must be finite and > 0, got {self.timeout!r} — "
                "an infinite master patience is the reference's "
                "hang-forever semantics, which this controller exists to "
                "remove"
            )
        if self.min_workers < 1:
            raise ValueError(
                f"min_workers must be >= 1, got {self.min_workers}"
            )
        if self.shift_factor <= 1.0:
            raise ValueError(
                f"shift_factor must be > 1, got {self.shift_factor}"
            )
        if self.absence_rounds is not None and (
            self.absence_rounds < self.death_rounds
        ):
            raise ValueError(
                f"absence_rounds ({self.absence_rounds}) must be >= "
                f"death_rounds ({self.death_rounds}) — the no-evidence "
                "backstop cannot be stricter than the evidential rule"
            )


@dataclasses.dataclass(frozen=True)
class ChunkObservation:
    """What one chunk's telemetry told the detector."""

    first_round: int
    #: workers newly suspected dead this chunk (streak >= threshold);
    #: they become deaths at the next commit
    deaths: tuple
    #: the collapsed-arrival probe fired (shift_factor jump)
    collapse: bool
    #: masked mean arrival of the chunk (None = nobody arrived)
    arrival_mean: Optional[float]


@dataclasses.dataclass(frozen=True)
class MembershipChange:
    """One committed re-layout: who left, who joined, W -> W'."""

    round: int
    dead: tuple
    joined: tuple
    n_workers_before: int
    n_workers_after: int


class MembershipController:
    """Tracks the believed-alive worker set from per-chunk telemetry
    (class docstring). Worker ids are ORIGINAL ids — the layout over W'
    survivors maps its columns back through :attr:`active`."""

    def __init__(self, n_workers: int, cfg: ElasticConfig = None):
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        self.cfg = cfg or ElasticConfig()
        self.n_workers = int(n_workers)
        self.active: tuple = tuple(range(n_workers))
        self.dead: tuple = ()
        self.epoch = 0
        self._streaks = {w: 0 for w in range(n_workers)}
        self._absence = {w: 0 for w in range(n_workers)}
        self._last_mean: Optional[float] = None
        self._pending_deaths: list = []
        self._pending_joins: list = []
        self.decisions: list[dict] = []

    # ---- telemetry feedback ----------------------------------------------

    def observe_chunk(
        self,
        first_round: int,
        worker_times: np.ndarray,
        sim_time: Optional[np.ndarray] = None,
        window: Optional[float] = None,
    ) -> ChunkObservation:
        """Feed one chunk's [n, W'] telemetry clock block (columns in
        :attr:`active` order, carrying the -1 never-collected sentinel).
        Updates suspicion streaks and the collapse detector; newly
        suspected workers become pending deaths, applied at the next
        :meth:`commit`.

        ``sim_time`` is the chunk's [n] per-round simulated clock and
        ``window`` the master's per-round listening window (the driver
        passes ``min(timeout, deadline)``): a round is EVIDENTIAL only
        when its clock ran the window out — a sentinel in a round the
        master ended early means "stopped listening", not "dead" (module
        docstring). Without ``sim_time`` every round counts as
        evidential (the raw detect_dead view of a clock block)."""
        from erasurehead_tpu.obs import events as obs_events
        from erasurehead_tpu.parallel import failures

        wt = np.asarray(worker_times, dtype=np.float64)
        if wt.ndim != 2 or wt.shape[1] != len(self.active):
            raise ValueError(
                f"worker_times shape {wt.shape} does not match the "
                f"{len(self.active)} active workers"
            )
        # detect_dead reads the sentinel AND the timeout trip in one rule:
        # negative (never collected) or beyond the master's patience
        suspect = failures.detect_dead(wt, self.cfg.timeout)
        if sim_time is None:
            evidential = np.ones(wt.shape[0], dtype=bool)
        else:
            win = self.cfg.timeout if window is None else float(window)
            evidential = (
                np.asarray(sim_time, dtype=np.float64)
                >= win * (1.0 - 1e-9)
            )
        for j, w in enumerate(self.active):
            col = suspect[:, j]
            streak = self._streaks.get(w, 0)
            absent = self._absence.get(w, 0)
            for r, s in enumerate(col):  # rounds in order
                if not s:
                    # an in-patience arrival resets both rules
                    streak = 0
                    absent = 0
                else:
                    absent += 1
                    if evidential[r]:
                        streak += 1
                    # non-evidential absence leaves the streak unchanged:
                    # absence of evidence is not evidence of life
            self._streaks[w] = int(streak)
            self._absence[w] = int(absent)
        K = self.cfg.death_rounds
        threshold = {w: K for w in self.active}

        # collapse probe: the adapt/ shift rule on the chunk's own masked
        # arrival stats — policy-independent enough here because a genuine
        # collapse moves the mean regardless of which workers arrive
        mean = obs_events.arrival_summary(wt)["mean"]
        prev_mean = self._last_mean
        collapse = False
        if mean is not None and prev_mean is not None:
            lo, hi = sorted((max(mean, 1e-12), max(prev_mean, 1e-12)))
            collapse = hi / lo >= self.cfg.shift_factor
        if mean is not None:
            self._last_mean = mean
        if collapse:
            # corroborated threshold: the collapse and a persistent silent
            # worker are evidence of one event — promote half-streaks
            half = max(1, math.ceil(K / 2))
            threshold = {w: half for w in self.active}

        pending = set(self._pending_deaths)
        absence_limit = self.cfg.effective_absence_rounds
        deaths = []
        for w in self.active:
            if w in pending:
                continue
            by_streak = self._streaks[w] >= threshold[w]
            by_absence = self._absence[w] >= absence_limit
            if by_streak or by_absence:
                deaths.append(w)
                self.decisions.append({
                    "action": "death", "round": int(first_round),
                    "worker": int(w), "streak": int(self._streaks[w]),
                    "absent": int(self._absence[w]),
                    "rule": "streak" if by_streak else "absence",
                    "threshold": int(threshold[w]),
                    "corroborated": bool(collapse),
                })
        self._pending_deaths.extend(deaths)
        if collapse:
            self.decisions.append({
                "action": "probe", "round": int(first_round),
                "arrival_mean": mean, "prev_mean": prev_mean,
            })
        return ChunkObservation(
            first_round=int(first_round),
            deaths=tuple(deaths),
            collapse=collapse,
            arrival_mean=mean,
        )

    # ---- join offers ------------------------------------------------------

    def request_join(self, worker: int, round: int = 0) -> bool:
        """Queue a join offer for ``worker`` (an original id). Returns
        False (ignored) when the worker is already active or queued."""
        w = int(worker)
        if not 0 <= w < self.n_workers:
            raise ValueError(
                f"join offer for worker {w} outside [0, {self.n_workers})"
            )
        if w in self.active or w in self._pending_joins:
            return False
        self._pending_joins.append(w)
        self.decisions.append({
            "action": "join", "round": int(round), "worker": w,
        })
        return True

    # ---- commit -----------------------------------------------------------

    def commit(self, round: int) -> Optional[MembershipChange]:
        """Apply pending deaths and joins at a chunk boundary; returns the
        change (triggering a re-layout) or None when membership is
        unchanged. Deaths are dropped lowest-id-first if applying all of
        them would shrink below ``min_workers`` (deterministic; the kept
        suspects stay pending and re-commit once joins restore headroom)."""
        before = self.active
        deaths = sorted(set(self._pending_deaths) & set(before))
        joins = sorted(
            w for w in self._pending_joins if w not in before
        )
        new = [w for w in before if w not in deaths] + joins
        if len(new) < self.cfg.min_workers:
            keep = self.cfg.min_workers - len(new)
            kept, deaths = deaths[:keep], deaths[keep:]
            new = sorted(new + kept)
        if not new:
            raise RuntimeError("membership commit left zero workers")
        new = tuple(sorted(new))
        applied = set(deaths)
        # suspects kept alive by the min_workers floor stay pending — they
        # re-commit as soon as a join restores headroom
        self._pending_deaths = [
            w for w in self._pending_deaths
            if w not in applied and w in new
        ]
        self._pending_joins = []
        if new == before:
            return None
        self.active = new
        self.dead = tuple(sorted(set(range(self.n_workers)) - set(new)))
        for w in joins:
            self._streaks[w] = 0  # a joiner starts with a clean slate
            self._absence[w] = 0
        self.epoch += 1
        change = MembershipChange(
            round=int(round),
            dead=tuple(deaths),
            joined=tuple(joins),
            n_workers_before=len(before),
            n_workers_after=len(new),
        )
        self.decisions.append({
            "action": "relayout", "round": int(round),
            "dead": list(change.dead), "joined": list(change.joined),
            "n_workers": len(new), "epoch": self.epoch,
        })
        return change

    # ---- persistence ------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-serializable full state (checkpoint aux sidecar): restoring
        it and replaying the same telemetry reproduces the same decisions."""
        return {
            "n_workers": self.n_workers,
            "active": list(self.active),
            "dead": list(self.dead),
            "epoch": self.epoch,
            "streaks": {str(w): s for w, s in self._streaks.items()},
            "absence": {str(w): s for w, s in self._absence.items()},
            "last_mean": self._last_mean,
            "pending_deaths": list(self._pending_deaths),
            "pending_joins": list(self._pending_joins),
            "decisions": list(self.decisions),
        }

    @classmethod
    def restore(
        cls, snap: dict, cfg: ElasticConfig = None
    ) -> "MembershipController":
        ctl = cls(int(snap["n_workers"]), cfg)
        ctl.active = tuple(int(w) for w in snap["active"])
        ctl.dead = tuple(int(w) for w in snap["dead"])
        ctl.epoch = int(snap["epoch"])
        ctl._streaks = {int(w): int(s) for w, s in snap["streaks"].items()}
        ctl._absence = {
            int(w): int(s)
            for w, s in snap.get("absence", {}).items()
        }
        ctl._last_mean = snap.get("last_mean")
        ctl._pending_deaths = [int(w) for w in snap["pending_deaths"]]
        ctl._pending_joins = [int(w) for w in snap["pending_joins"]]
        ctl.decisions = list(snap.get("decisions", []))
        return ctl


class ProbeStreakDetector:
    """The evidential-streak death rule, generalized to probe-based
    membership over NAMED members (string ids, not worker columns).

    This is the same discipline :class:`MembershipController` applies to
    telemetry columns, lifted out for callers that watch liveness through
    explicit probes — the serve fleet (serve/fleet.py) probing each
    replica's ``/healthz``: a member is declared dead only after ``k``
    CONSECUTIVE *evidential* misses. A probe is evidential only when it
    was actually ATTEMPTED and ran its window out (connect refused, read
    timeout, bad status); a probe the caller never made — the prober was
    paused, the member was deliberately drained for a rolling deploy —
    is not evidence, and leaves the streak unchanged (absence of
    evidence is not evidence of life, and equally not of death). One
    success resets the streak to zero. Never one timeout: ``k >= 2`` is
    enforced, because a single miss declaring death is exactly the
    reference's raw-timeout semantics this module exists to remove.
    """

    def __init__(self, members: Sequence[str] = (), k: int = 3):
        if k < 2:
            raise ValueError(
                f"k must be >= 2, got {k} — a single evidential miss "
                "declaring death is a raw timeout, not a streak rule"
            )
        self.k = int(k)
        self._streaks: dict[str, int] = {str(m): 0 for m in members}
        self._dead: set[str] = set()

    @property
    def members(self) -> tuple:
        return tuple(sorted(self._streaks))

    def add(self, member: str) -> None:
        """(Re)admit a member with a clean slate — a joiner (or a
        bounced replica re-entering the ring) starts at streak zero."""
        m = str(member)
        self._streaks[m] = 0
        self._dead.discard(m)

    def remove(self, member: str) -> None:
        m = str(member)
        self._streaks.pop(m, None)
        self._dead.discard(m)

    def observe(
        self, member: str, ok: bool, evidential: bool = True
    ) -> int:
        """Feed one probe outcome; returns the member's updated streak.
        ``ok`` resets the streak; a miss advances it only when the probe
        was evidential (actually attempted to completion)."""
        m = str(member)
        if m not in self._streaks:
            raise KeyError(f"unknown member {m!r}")
        if ok:
            self._streaks[m] = 0
            self._dead.discard(m)
        elif evidential:
            self._streaks[m] += 1
            if self._streaks[m] >= self.k:
                self._dead.add(m)
        return self._streaks[m]

    def streak(self, member: str) -> int:
        return self._streaks[str(member)]

    def is_dead(self, member: str) -> bool:
        return str(member) in self._dead

    def snapshot(self) -> dict:
        return {
            "k": self.k,
            "streaks": dict(self._streaks),
            "dead": sorted(self._dead),
        }

    @classmethod
    def restore(cls, snap: dict) -> "ProbeStreakDetector":
        det = cls(k=int(snap["k"]))
        det._streaks = {
            str(m): int(s) for m, s in snap["streaks"].items()
        }
        det._dead = {str(m) for m in snap.get("dead", [])}
        return det


def auto_survivor_config(
    cfg, n_active: int, survivor_overrides: Optional[dict] = None,
    lr_schedule=None,
):
    """A validated config for ``n_active`` workers, auto-shrinking
    ``n_stragglers`` when the scheme's structural constraint (FRC's
    ``(s+1) | W'``) rejects the current value.

    An explicit ``n_stragglers`` in ``survivor_overrides`` is honored
    as-is (its failure propagates — the caller asked for exactly that);
    otherwise the controller tries s, s-1, ..., 0 and takes the largest
    valid value, so an online re-layout never dies on a divisibility
    accident the operator is not around to fix. Returns the config (the
    chosen s is readable off it)."""
    from erasurehead_tpu.parallel import failures

    explicit = (survivor_overrides or {}).get("n_stragglers") is not None
    if explicit:
        return failures.survivor_config(
            cfg, n_active, survivor_overrides, lr_schedule=lr_schedule
        )
    last_err = None
    for s in range(cfg.n_stragglers, -1, -1):
        ov = dict(survivor_overrides or {})
        ov["n_stragglers"] = s
        try:
            return failures.survivor_config(
                cfg, n_active, ov, lr_schedule=lr_schedule
            )
        except ValueError as e:
            last_err = e
    raise last_err


def default_join_offers(
    revives, active: Sequence[int], boundary_round: int
) -> list[int]:
    """Scripted revives (``{worker: round}``) whose round has passed and
    whose worker is not in the active layout — the scripted counterpart
    of a chaos ``worker_revive`` offer."""
    if not revives:
        return []
    act = set(active)
    return sorted(
        int(w)
        for w, r in revives.items()
        if int(r) <= boundary_round and int(w) not in act
    )
