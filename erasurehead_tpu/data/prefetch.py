"""Host→device prefetch pipeline for streamed partition stacks.

The streamed trainer (train/trainer.py, ``stack_residency="streamed"``)
consumes the shard store (data/store.py) one partition window per scan
chunk. Left naive, every chunk boundary would serialize
disk→host→device→compute; this module applies the same prologue/epilogue
pipelining discipline as parallel/step's ring fill, on the host→device
axis: while chunk ``i`` computes on device, a staging thread reads window
``i+1`` from the shard mmaps into a bounded ring of reusable host
buffers and ``jax.device_put``s it behind the running computation —
dispatch is async, so the transfer overlaps the chunk that is already
executing. ``get(i)`` then hands the trainer device-resident arrays,
blocking only for whatever transfer time compute failed to hide (the
blocked seconds are the pipeline's measured overhead; ``stats()`` turns
them into the prefetch-overlap efficiency the bench extra reports).

The ring is bounded (``depth`` windows, default 2 = classic double
buffering), so host memory holds at most ``depth`` windows regardless of
dataset size — the whole point of streaming. A host buffer is reused
only after its device transfer commits (``block_until_ready`` on the
staged leaves), never while a copy may still be reading it.

Every staged window fires the ``prefetch`` chaos site
(utils/chaos.maybe_fire — ``ERASUREHEAD_CHAOS=kill:prefetch:N`` is a
mid-epoch preemption for the kill→resume harness, tools/
outofcore_smoke.py) and emits a typed ``prefetch`` event into the
current capture (obs/events.py).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional, Sequence

import jax
import numpy as np

from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.utils import chaos as chaos_lib

#: ring depth: the window being consumed + the window in flight. Deeper
#: rings only help when read time varies a lot between windows; they cost
#: host RAM proportionally.
DEFAULT_DEPTH = 2


def _norm_window(spec) -> tuple:
    """Normalize one consume-order entry to a tuple of (lo, hi) ranges:
    a plain ``(lo, hi)`` pair (the PR 14 partition windows) or an
    assignment-aware plan's range tuple (data/sharding.StreamWindowPlan.
    ranges[k] — two ranges when the slot-group halo wraps)."""
    spec = tuple(spec)
    if len(spec) == 2 and not isinstance(spec[0], (tuple, list)):
        return ((int(spec[0]), int(spec[1])),)
    return tuple((int(lo), int(hi)) for lo, hi in spec)


class Prefetcher:
    """Bounded staging pipeline over a schedule of partition windows.

    ``windows`` is the exact consume-order sequence of windows the
    trainer will request — one entry per scan chunk, repeats allowed
    (epochs revisit windows). Each entry is a ``(lo, hi)`` partition
    range or a tuple of such ranges (an assignment-aware window plan's
    staged span, in ring-hop order — see data/sharding.
    StreamWindowPlan). ``put`` maps the host arrays of one window to
    device arrays (the trainer passes its sharded ``device_put``); it
    runs on the staging thread, which is the overlap. ``get(i)`` must be
    called for ``i = 0, 1, ...`` in order. ``plan_fields`` (a dict, e.g.
    ``StreamWindowPlan.event_fields()``) rides every staged ``prefetch``
    event — the window-plan contract obs/events.SCHEMA validates.

    Errors on the staging thread (a torn store, a chaos ``raise``)
    surface at the next ``get`` call — never silently, never deadlocked
    (the ring slot the failed stage held is released with the error).
    """

    def __init__(
        self,
        store,
        windows: Sequence[tuple],
        put: Callable,
        *,
        depth: int = DEFAULT_DEPTH,
        run_id: Optional[str] = None,
        plan_fields: Optional[dict] = None,
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.store = store
        self.windows = [_norm_window(w) for w in windows]
        self._put = put
        self.run_id = run_id
        self._plan_fields = dict(plan_fields or {})
        self._ready: queue.Queue = queue.Queue(maxsize=depth)
        # depth reusable host-buffer sets; slot i % depth backs window i,
        # safe because the staging thread blocks the transfer to
        # completion before moving on and the ready queue holds at most
        # depth windows
        self._bufs = [dict() for _ in range(depth)]
        self._next_get = 0
        self._blocked_s = 0.0
        self._blocked_after_first_s = 0.0
        self._fetch_s = 0.0
        self._fetch_after_first_s = 0.0
        self._bytes = 0
        self._staged = 0
        self._thread = threading.Thread(
            target=self._run, name="eh-prefetch", daemon=True
        )
        self._thread.start()

    # -- staging thread ---------------------------------------------------

    def _run(self) -> None:
        for i, ranges in enumerate(self.windows):
            try:
                chaos_lib.maybe_fire("prefetch")
                t0 = time.perf_counter()
                X, y = self.store.read_ranges(
                    ranges, out=self._bufs[i % len(self._bufs)]
                )
                dev = self._put(X, y)
                # commit the transfer before the slot can be reused (and
                # so fetch_s measures disk + PCIe, not dispatch)
                jax.block_until_ready(dev)
                dt = time.perf_counter() - t0
                n_bytes = sum(
                    np.asarray(leaf).nbytes
                    for leaf in jax.tree.leaves((X, y))
                )
            except BaseException as e:  # noqa: BLE001 — repaired at get()
                self._ready.put((i, None, e))
                return
            self._fetch_s += dt
            if i:
                self._fetch_after_first_s += dt
            self._bytes += n_bytes
            self._staged += 1
            if self.run_id is not None:
                events_lib.emit(
                    "prefetch",
                    run_id=self.run_id,
                    window=i,
                    bytes=n_bytes,
                    partitions=[ranges[0][0], ranges[0][1]],
                    ranges=[[lo, hi] for lo, hi in ranges],
                    fetch_s=round(dt, 6),
                    **self._plan_fields,
                )
            self._ready.put((i, dev, None))

    # -- consumer side ----------------------------------------------------

    def get(self, i: int):
        """Device arrays for window ``i`` (strictly in-order). Blocks
        until staged; the wait is recorded as unhidden transfer time."""
        if i != self._next_get:
            raise ValueError(
                f"prefetch windows are consumed in order; expected "
                f"{self._next_get}, got {i}"
            )
        t0 = time.perf_counter()
        idx, dev, err = self._ready.get()
        waited = time.perf_counter() - t0
        self._blocked_s += waited
        if i:
            self._blocked_after_first_s += waited
        if err is not None:
            raise err
        assert idx == i, f"prefetch ring out of order: {idx} != {i}"
        self._next_get += 1
        return dev

    def stats(self) -> dict:
        """Pipeline telemetry for cache_info / the bench extra.

        ``overlap_efficiency`` is the fraction of steady-state transfer
        time hidden behind compute: 1 - blocked/fetch over every window
        AFTER the first (the prologue window has nothing to hide
        behind). 1.0 when a single window made the question moot."""
        fetch = self._fetch_after_first_s
        blocked = self._blocked_after_first_s
        eff = 1.0 if fetch <= 0 else max(0.0, 1.0 - blocked / fetch)
        return {
            "windows": self._staged,
            "bytes": int(self._bytes),
            "fetch_s": round(self._fetch_s, 6),
            "blocked_s": round(self._blocked_s, 6),
            "overlap_efficiency": round(eff, 4),
        }

    def close(self, join_timeout_s: float = 10.0) -> None:
        """Drain and join the staging thread (idempotent).

        Bounded: a WEDGED stage (a hung NFS read, a device transfer that
        never completes) used to spin the drain loop forever — and even
        once it reached the join, a join timeout was silently swallowed,
        leaking the daemon thread (and whatever mmap/host-buffer state it
        pinned) with no trace. Now the whole drain+join observes one
        ``join_timeout_s`` deadline, and a thread that outlives it is
        reported loudly: a ``warn_once`` on stderr, a
        ``prefetch.join_timeout`` telemetry counter, and a typed
        ``warning`` event (kind="prefetch_join_timeout") in the current
        capture. The thread is daemonic, so the leak never blocks process
        exit — but it is a leak, and leaks must be visible."""
        t = self._thread
        if t is None:
            return
        self._thread = None
        deadline = time.monotonic() + max(0.0, float(join_timeout_s))
        while True:
            try:
                self._ready.get_nowait()
            except queue.Empty:
                if not t.is_alive() or time.monotonic() >= deadline:
                    break
                time.sleep(0.005)
        t.join(timeout=max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            from erasurehead_tpu.obs.metrics import REGISTRY, warn_once

            REGISTRY.counter("prefetch.join_timeout").inc()
            msg = (
                f"prefetch staging thread {t.name!r} did not exit within "
                f"{float(join_timeout_s):g}s of close(); a stage is "
                "wedged (hung shard read or device transfer) and the "
                "daemon thread leaks until process exit"
            )
            warn_once("prefetch-join-timeout", msg)
            extra = (
                {"run_id": self.run_id} if self.run_id is not None else {}
            )
            events_lib.emit(
                "warning",
                kind="prefetch_join_timeout",
                message=msg,
                **extra,
            )

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
