"""Partitioning datasets and materializing (possibly redundant) worker stacks.

The reference shards by writing one file per partition to NFS and having each
MPI rank load its assigned (rotated/replicated) partitions at startup
(src/approximate_coding.py:39-69). Here the same assignment becomes array
indexing: a partition-major stack [P, rows, F], and — for the faithful
compute mode — a worker-major stack [W, S, rows, F] gathered through
``CodingLayout.assignment`` (the redundancy is real memory, as it was real
disk+RAM in the reference). Stacks are then device_put sharded over the
worker mesh axis.

``stack_mode="ring"`` drops the materialized redundancy: only the
partition-major stack is resident, and each device reconstructs its
workers' slot buffer per step from its ring neighbors' shards over
``lax.ppermute`` hops (:class:`RingPlan`; the grad body lives in
parallel/step.make_ring_faithful_grad_fn). Same science, (s+1)x less
device data.

Row-count convention matched to the reference: rows_per_partition =
n_samples // P with trailing remainder rows dropped from training
(src/coded.py:23's integer division; the remainder still appears in the
eval-replay train set there — we drop it consistently instead, documented
deviation).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import numpy as np
import scipy.sparse as sps

from erasurehead_tpu.data.synthetic import Dataset
from erasurehead_tpu.ops.codes import CodingLayout
from erasurehead_tpu.ops.features import (
    Features,
    FieldOnehot,
    PaddedRows,
    QuantizedStack,
    infer_field_sizes,
)
from erasurehead_tpu.parallel import mesh as mesh_lib


@dataclasses.dataclass
class ShardedData:
    """Device-resident training data for one run."""

    Xp: Features  # [P, rows, F] partition-major (deduped mode), sharded
    yp: jax.Array  # [P, rows]
    Xw: Optional[Features]  # [W, S, rows, F] worker-major (faithful), sharded
    yw: Optional[jax.Array]  # [W, S, rows]
    n_train: int  # rows actually trained on (P * rows_per_partition)


def partition_stack(dataset: Dataset, n_partitions: int, sparse_format="padded"):
    """[P, rows, F] + [P, rows] partition-major arrays (host).

    ``sparse_format`` picks the sparse stack representation (RunConfig
    docs): "padded" (PaddedRows), "fields" (FieldOnehot; raises when the
    data is not exactly-one-hot-per-field), or "auto" (fields when the
    structure allows, else padded).
    """
    n = dataset.n_samples
    rows = n // n_partitions
    if rows == 0:
        raise ValueError(f"{n} samples cannot fill {n_partitions} partitions")
    X, y = dataset.X_train, dataset.y_train
    if sps.issparse(X):
        X = X[: rows * n_partitions]
        # field structure is a whole-matrix property: infer once so every
        # partition shares the same block offsets (tables must agree)
        sizes = None
        if sparse_format in ("fields", "auto"):
            sizes = infer_field_sizes(X)
            if sizes is None and sparse_format == "fields":
                raise ValueError(
                    "sparse_format='fields' requires exactly-one-hot-per-"
                    "field data (uniform nnz/row, unit values, disjoint "
                    "ordered field blocks); use 'auto' or 'padded'"
                )
        parts = [X[i * rows : (i + 1) * rows] for i in range(n_partitions)]
        if sizes is not None:
            # from_scipy returns host numpy leaves, so this stays on host
            Xp = jax.tree.map(
                lambda *leaves: np.stack(leaves),
                *[
                    FieldOnehot.from_scipy(p, field_sizes=sizes)
                    for p in parts
                ],
            )
        else:
            nnz = max(int(np.diff(p.indptr).max()) for p in parts)
            Xp = jax.tree.map(
                lambda *leaves: np.stack(leaves),
                *[_padded_host(p, nnz) for p in parts],
            )
    else:
        if sparse_format == "fields":
            raise ValueError(
                "sparse_format='fields' requires sparse (CSR) features; "
                "this dataset is dense — use 'auto' or 'padded'"
            )
        Xp = X[: rows * n_partitions].reshape(n_partitions, rows, -1)
    yp = y[: rows * n_partitions].reshape(n_partitions, rows)
    return Xp, yp


def _padded_host(csr, nnz):
    P = PaddedRows.from_scipy(csr, nnz)
    return PaddedRows(np.asarray(P.indices), np.asarray(P.values), P.n_cols)


def worker_stack(layout: CodingLayout, Xp, yp):
    """Gather the redundant worker-major stacks through the assignment.

    Container stacks (PaddedRows, FieldOnehot, QuantizedStack) gather
    leaf-wise: every leaf leads with the partition axis, so one indexed
    take per leaf — a QuantizedStack's scale table rides the same gather
    as its payload."""
    take = lambda A: (
        jax.tree.map(lambda leaf: leaf[layout.assignment], A)
        if isinstance(A, (PaddedRows, FieldOnehot, QuantizedStack))
        else A[layout.assignment]
    )
    return take(Xp), yp[layout.assignment]


# ---------------------------------------------------------------------------
# Ring-streamed faithful stack (stack_mode="ring")
# ---------------------------------------------------------------------------

#: stack_mode="auto" switches faithful runs to the ring transport once the
#: MATERIALIZED worker stack would exceed this many device bytes (per
#: replica of the data, summed over the mesh). Below it, the redundant
#: stack is cheap and the materialized mode keeps its zero-transport step.
RING_AUTO_MIN_BYTES = 1 << 30


@dataclasses.dataclass(frozen=True)
class RingPlan:
    """Static transport plan turning the partition-major stack into each
    device's worker-major slot buffer via ring neighbor hops.

    The faithful mode's redundancy is *structured*: cyclic MDS/AGC
    assignments give worker ``w`` partitions ``{w..w+s} mod P`` and FRC
    groups are block-local, so every redundant partition is the primary
    partition of a near ring-neighbor device. Instead of materializing the
    ``[W, S, rows, F]`` stack ((s+1)x the data in HBM), each device keeps
    only its ``[Pl, rows, F]`` partition shard and receives the blocks it
    is missing over ``n_hops - 1`` ``lax.ppermute`` neighbor hops (the
    same ICI pattern as parallel/ring.py's ring attention).

    ``sel[d, h, wl, s]`` is the index INTO THE VISITING BLOCK (the
    partition shard originally owned by device ``(d + h) % D``) that fills
    local worker ``wl``'s slot ``s`` on device ``d`` at fill-step ``h``,
    or -1 when that slot is not filled at this hop. Hop 0 is the device's
    own block (no communication); ring-local assignments need
    ``1 + ceil(s / Pl)`` fill steps, and an arbitrary (non-ring-local)
    assignment degrades gracefully to at most a full rotation of ``D``
    fill steps — the general fallback is the same program with more hops,
    never a different code path.
    """

    n_devices: int
    n_hops: int  # fill steps; n_hops - 1 ppermutes per gradient step
    sel: np.ndarray  # [D, n_hops, Wl, S] int32, -1 = not filled this hop

    @property
    def local_workers(self) -> int:
        return self.sel.shape[2]

    @property
    def n_slots(self) -> int:
        return self.sel.shape[3]


def plan_ring_transport(layout: CodingLayout, n_devices: int) -> RingPlan:
    """Build the :class:`RingPlan` for ``layout`` on a ``n_devices`` ring.

    Requires both the worker axis (W, the compute sharding) and the
    partition axis (P, the data sharding) to fold evenly onto the ring;
    every layout family here has P a multiple of W, so any device count
    dividing W works.
    """
    W, S, P = layout.n_workers, layout.n_slots, layout.n_partitions
    D = int(n_devices)
    if W % D or P % D:
        raise ValueError(
            f"ring stack mode needs n_workers={W} and n_partitions={P} "
            f"divisible by the {D} worker-axis devices"
        )
    Wl, Pl = W // D, P // D
    assignment = np.asarray(layout.assignment)
    sel = np.full((D, _ring_hops(layout, D), Wl, S), -1, dtype=np.int32)
    for w in range(W):
        d = w // Wl
        for s in range(S):
            p = int(assignment[w, s])
            hop = (p // Pl - d) % D
            sel[d, hop, w % Wl, s] = p % Pl
    return RingPlan(n_devices=D, n_hops=sel.shape[1], sel=sel)


def _ring_hops(layout: CodingLayout, n_devices: int) -> int:
    """Fill steps needed: 1 + the farthest forward ring distance from any
    worker's device to a device owning one of its assigned partitions."""
    W, P = layout.n_workers, layout.n_partitions
    D = n_devices
    Wl, Pl = W // D, P // D
    assignment = np.asarray(layout.assignment)
    dev_of_w = np.arange(W)[:, None] // Wl
    hop = (assignment // Pl - dev_of_w) % D
    return int(hop.max()) + 1


# ---------------------------------------------------------------------------
# Assignment-aware stream windows (stack_residency="streamed" composing with
# the faithful/ring stacks; train/trainer._train_streamed)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _WindowedLayout:
    """Layout view over ONE staged window of a :class:`StreamWindowPlan` —
    exactly the four attributes :func:`plan_ring_transport` reads, with the
    assignment LOCALIZED to staged-buffer indices. Every window shares this
    view (window-uniformity is enforced by the planner), which is what lets
    one compiled chunk executable — and one ring hop table — serve every
    window of the stream."""

    n_workers: int
    n_slots: int
    n_partitions: int
    assignment: np.ndarray  # [gw, S] indices into the staged stack


@dataclasses.dataclass(frozen=True)
class StreamWindowPlan:
    """Assignment-aware window plan for a streamed run.

    PR 14's windows were windows of the PARTITION axis under the one
    deduped body — an assignment never entered. The faithful/ring stacks
    gather through ``CodingLayout.assignment``, so their windows must be
    windows of the CODED ASSIGNMENT: contiguous slot-groups of
    ``group_workers`` workers whose assigned partitions all fall inside
    the staged span ``[k*window, k*window + window + halo) mod P``. The
    ``halo`` is the assignment's forward reach past the window edge (for
    the cyclic ``{w..w+s} mod P`` supports it is exactly ``s``) — those
    partitions are the head of the NEXT window, so each scan chunk's ring
    fill (parallel/step._ring_fill) touches only partitions resident in
    the current + in-flight window, and at most two staged windows of
    device bytes are ever pinned.

    ``ranges[k]`` is the tuple of contiguous partition ranges the
    Prefetcher stages for window ``k`` (two when the halo wraps the
    partition axis), ordered so staged-buffer position ``i`` holds
    partition ``(k*window + i) mod P`` — ring-hop order: position
    ``i``'s block arrives at ring fill-step ``i // (staged/D)``, so the
    buffer layout IS the hop schedule. ``local_assignment[wl, s]`` maps
    slot-group worker ``wl``'s slot ``s`` to its staged-buffer index;
    the planner refuses assignments that are not window-uniform (e.g.
    random-regular scatter), because those would need a different hop
    table — a different compiled program — per window.

    ``mode="deduped"`` plans degenerate to the PR 14 partition windows
    (halo 0, no slot-groups) so one plan type describes every streamed
    body."""

    mode: str  # "deduped" | "materialized" | "ring"
    n_partitions: int
    window: int  # partition-window size (divides P)
    n_windows: int
    halo: int  # staged partitions past the window edge (0 for deduped)
    group_workers: int  # workers per slot-group (0 for deduped)
    ranges: tuple  # per window k: ((lo, hi), ...) contiguous staged ranges
    local_assignment: Optional[np.ndarray]  # [gw, S] staged-buffer indices

    @property
    def staged_partitions(self) -> int:
        """Partitions materialized per staged window (window + halo) —
        the residency unit admission and the bench extra charge in."""
        return self.window + self.halo

    def sub_layout(self) -> _WindowedLayout:
        """The one-window layout view a sub-:class:`RingPlan` is built
        over (``plan_ring_transport(plan.sub_layout(), D)``). Full-cover
        plans localize to the identity shift, so the sub-plan's hop table
        is byte-identical to the resident ring plan's — the bitwise
        streamed+ring == resident+ring pin rests on this."""
        if self.local_assignment is None:
            raise ValueError(
                "deduped stream windows have no slot-groups (no ring "
                "transport to plan); sub_layout() is a faithful/ring-"
                "mode call"
            )
        return _WindowedLayout(
            n_workers=self.group_workers,
            n_slots=int(self.local_assignment.shape[1]),
            n_partitions=self.staged_partitions,
            assignment=self.local_assignment,
        )

    def event_fields(self) -> dict:
        """The window-plan fields every staged ``prefetch`` event carries
        (obs/events.SCHEMA) — what the report and the lint contract key
        the composed-streaming telemetry on."""
        return {
            "plan_mode": self.mode,
            "halo": int(self.halo),
            "group_workers": int(self.group_workers),
        }


def plan_stream_windows(
    layout: CodingLayout, window: int, *, mode: str = "deduped"
) -> StreamWindowPlan:
    """Plan the staged windows a streamed run of ``layout`` consumes.

    ``window`` is the partition-window size (a divisor of P, from
    trainer._resolve_stream_window). Deduped plans are pure partition
    windows. Faithful/ring plans split the worker axis into
    ``P // window`` contiguous slot-groups and stage each group's full
    assigned partition span — window plus halo — refusing loudly when
    the worker axis does not split evenly or the assignment is not
    window-uniform (one compiled chunk must serve every window; see
    :class:`StreamWindowPlan`)."""
    P = int(layout.n_partitions)
    window = int(window)
    if window < 1 or P % window:
        raise ValueError(
            f"stream window must be a divisor of n_partitions={P}, "
            f"got {window}"
        )
    n_windows = P // window
    if mode == "deduped":
        return StreamWindowPlan(
            mode=mode,
            n_partitions=P,
            window=window,
            n_windows=n_windows,
            halo=0,
            group_workers=0,
            ranges=tuple(
                ((k * window, (k + 1) * window),) for k in range(n_windows)
            ),
            local_assignment=None,
        )
    if mode not in ("materialized", "ring"):
        raise ValueError(
            f"stream window mode must be 'deduped', 'materialized' or "
            f"'ring', got {mode!r}"
        )
    W = int(layout.n_workers)
    if W % n_windows:
        raise ValueError(
            f"{W} workers cannot split into {n_windows} equal slot-groups "
            f"(window {window} of {P} partitions); pick a stream window "
            f"whose count divides the worker axis"
        )
    gw = W // n_windows
    assignment = np.asarray(layout.assignment)
    local = None
    halo = 0
    for k in range(n_windows):
        loc = (assignment[k * gw : (k + 1) * gw] - k * window) % P
        halo = max(halo, int(loc.max()) + 1 - window)
        if local is None:
            local = loc.astype(np.int64)
        elif not np.array_equal(local, loc):
            raise ValueError(
                f"assignment is not window-uniform: slot-group {k} "
                f"touches a different local partition pattern than group "
                "0, so no single chunk executable (or ring hop table) can "
                "serve every window — run this scheme resident, or with "
                "a stream window covering every partition"
            )
    halo = max(0, min(halo, P - window))
    staged = window + halo
    ranges = []
    for k in range(n_windows):
        lo = k * window
        hi = lo + staged
        ranges.append(
            ((lo, hi),) if hi <= P else ((lo, P), (0, hi - P))
        )
    return StreamWindowPlan(
        mode=mode,
        n_partitions=P,
        window=window,
        n_windows=n_windows,
        halo=halo,
        group_workers=gw,
        ranges=tuple(ranges),
        local_assignment=local,
    )


def estimate_worker_stack_bytes(dataset: Dataset, layout: CodingLayout, dtype) -> int:
    """Host-side estimate of the MATERIALIZED faithful stack's device bytes
    (the stack_mode="auto" footprint gate). Dense: W * S * rows * F *
    itemsize; sparse stacks are scaled from the CSR payload (indices +
    values per stored entry). An estimate, not an accounting — the gate
    only has to separate "redundancy is real HBM pressure" from "noise"."""
    X = dataset.X_train
    rows = dataset.n_samples // layout.n_partitions
    dtype = np.dtype(dtype)
    if sps.issparse(X):
        nnz_per_row = X.nnz / max(1, X.shape[0])
        per_row = nnz_per_row * (np.dtype(np.int32).itemsize + dtype.itemsize)
    else:
        per_row = X.shape[1] * dtype.itemsize
    est = int(layout.n_workers * layout.n_slots * rows * per_row)
    if dtype == np.int8:
        # a quantized stack is payload PLUS one f32 scale row per slot
        # block (QuantizedStack.scale, [W, S, F] after the worker gather)
        # — counting payload alone undercharges every int8 admission and
        # auto-gate decision by W*S*F*4 bytes
        est += layout.n_workers * layout.n_slots * X.shape[1] * 4
    return est


def resolve_ring_stack(
    stack_mode: str,
    layout: CodingLayout,
    dataset: Dataset,
    n_devices: int,
    dtype,
    *,
    supported: bool = True,
) -> bool:
    """Should this faithful run stream its stack over the ring?

    "ring" forces (divisibility is validated by plan_ring_transport at use
    time); "materialized" keeps the reference's redundancy as real HBM;
    "auto" picks ring only when the redundant stack is actually redundant
    (storage_overhead > 1), folds onto this mesh, and — footprint verdict
    — either a cached ``stack_mode`` tune-race decision says "ring" at
    this pre-stack shape or, absent a measured verdict, the footprint
    estimate crosses RING_AUTO_MIN_BYTES. The tune consult replaces ONLY
    the threshold heuristic: the structural gates (redundancy,
    divisibility, support) are correctness-shaped and no measurement
    overrides them. ``supported=False`` (a trainer path with no ring
    body, e.g. measured mode) pins auto to materialized.
    """
    if stack_mode == "ring":
        return True
    if stack_mode != "auto" or not supported:
        return False
    if layout.storage_overhead <= 1.0:
        return False  # nothing redundant to stream
    W, P, D = layout.n_workers, layout.n_partitions, int(n_devices)
    if W % D or P % D:
        return False
    from erasurehead_tpu import tune as tune_lib

    rows = dataset.n_samples // layout.n_partitions
    sig = tune_lib.stack_mode_signature(
        layout, rows, dataset.X_train.shape[1], np.dtype(dtype).name
    )
    by_footprint = (
        estimate_worker_stack_bytes(dataset, layout, dtype)
        >= RING_AUTO_MIN_BYTES
    )
    choice = tune_lib.lookup(
        "stack_mode", sig,
        fallback="ring" if by_footprint else "materialized",
    )
    if choice is not None:
        return choice == "ring"
    return by_footprint


def np_global(x, dtype=None):
    """np.asarray that also works in a multi-controller cluster — the
    fetch-side counterpart of :func:`put_global`.

    Cluster cases a plain np.asarray cannot handle, each needing a
    DIFFERENT collective. Every process must take the same branch, so the
    branch keys on the sharding (identical everywhere), never on this
    process's own addressability:

    - the array spans all processes but is partitioned (XLA may leave jit
      outputs sharded): process_allgather reassembles the global value;
    - the array lives on a SUBMESH that excludes some processes (an
      elastic survivor phase folded onto fewer devices): the excluded
      processes hold nothing to gather — one owning process broadcasts.
      Decidable sub-cases: a single-process submesh (its owner reads the
      whole value) or a replicated multi-process submesh (any member
      holds a full local replica); a submesh both multi-process AND
      partitioned has no single reader and is refused consistently on
      every process.
    """
    if isinstance(x, jax.Array) and jax.process_count() > 1:
        from jax.experimental import multihost_utils
        from jax.sharding import SingleDeviceSharding

        if isinstance(x.sharding, SingleDeviceSharding):
            # HOST-LOCAL array (plain device_put / fresh init): every
            # process holds its own complete copy, and the sharding is
            # NOT globally consistent (each names its own local device) —
            # keying a collective on it would make every process the
            # "owner" and a broadcast would SUM the copies. Plain local
            # read is the complete, correct value — but ONLY for a
            # host-local array: an array explicitly device_put onto one
            # specific remote device also carries SingleDeviceSharding,
            # and a non-owning process has nothing to read (ADVICE r5 #2)
            if not x.is_fully_addressable:
                raise ValueError(
                    "np_global: array has SingleDeviceSharding on a device "
                    "this process does not own — a global single-device "
                    "placement is not host-local-replicated; fetch it on "
                    "the owning process or re-shard onto a mesh sharding "
                    "before the cross-process read"
                )
            return np.asarray(x, dtype)

        procs = {d.process_index for d in x.sharding.device_set}
        me = jax.process_index()
        if len(procs) < jax.process_count():
            if len(procs) == 1:
                owner = next(iter(procs))
                val = (
                    np.asarray(x)
                    if me == owner
                    else np.zeros(x.shape, x.dtype)
                )
            elif x.is_fully_replicated:
                owner = min(procs)
                val = (
                    np.asarray(x.addressable_shards[0].data)
                    if me == owner
                    else np.zeros(x.shape, x.dtype)
                )
            else:
                # consistent refusal on EVERY process — a one-sided raise
                # would strand the others inside the broadcast collective
                raise NotImplementedError(
                    "array partitioned across a strict subset of processes"
                )
            x = multihost_utils.broadcast_one_to_all(
                val, is_source=me == owner
            )
        elif not x.is_fully_addressable:
            x = multihost_utils.process_allgather(x, tiled=True)
    return np.asarray(x, dtype)


def put_global(leaf: np.ndarray, sharding) -> jax.Array:
    """Materialize a host array as a (possibly multi-host) sharded Array.

    Single-process: plain device_put. Multi-controller (a real pod via
    jax.distributed — parallel/backend.py): every process holds the full
    host array (data prep is seeded/deterministic, the reference's NFS
    share's analogue), and each contributes only its addressable shards via
    make_array_from_callback — device_put alone cannot build an array that
    spans non-addressable devices.
    """
    if jax.process_count() == 1:
        return jax.device_put(leaf, sharding)
    # dtype must be explicit (where the jax version allows): a process can
    # own ZERO shards of this array (e.g. an elastic survivor phase folded
    # onto a 1-device mesh) and then has no shard to infer it from
    from erasurehead_tpu.utils import compat

    return compat.make_array_from_callback(
        leaf.shape, sharding, lambda idx: leaf[idx], dtype=leaf.dtype
    )


def shard_run_data(
    dataset: Dataset,
    layout: CodingLayout,
    mesh,
    faithful: bool,
    dtype=np.float32,
    sparse_format: str = "padded",
    ring: bool = False,
    quantize: bool = False,
) -> ShardedData:
    """Build and device_put the stack the compute mode needs.

    Deduped mode shards partitions across devices (P % n_devices == 0);
    faithful mode shards logical workers (W % n_devices == 0) and skips the
    partition-major copy entirely (it would only waste HBM). Faithful with
    ``ring=True`` (stack_mode="ring") keeps ONLY the partition-major stack
    — the worker-major redundancy is reconstructed per step over ppermute
    neighbor hops (plan_ring_transport), so device and upload bytes drop
    by the layout's storage overhead ((s+1)x for the plain coded schemes).

    ``dtype`` is the DATA dtype: float32 default; bfloat16 halves HBM
    traffic on the bandwidth-bound gradient pass (params and optimizer
    state stay float32 — trainer-side mixed precision). Integer leaves
    (PaddedRows indices) are never cast.

    ``quantize=True`` (stack_dtype="int8") compresses the feature stack to
    a :class:`~erasurehead_tpu.ops.features.QuantizedStack` — int8 payload
    plus per-partition-per-feature f32 scale tables, quantized once per
    partition BEFORE any worker-major gather so materialized faithful,
    ring, and deduped stacks all carry the identical quantized values
    (their trajectories stay bitwise-comparable to each other). Dense
    stacks only; labels keep the ``dtype`` cast. The scale leaves are
    never down-cast (precision of the reconstruction, not traffic —
    they are O(P*F)).
    """
    Xp_h, yp_h = partition_stack(
        dataset, layout.n_partitions, sparse_format=sparse_format
    )
    sharding = mesh_lib.worker_sharding(mesh)
    dtype = np.dtype(dtype) if not hasattr(dtype, "itemsize") else dtype
    if quantize:
        if not isinstance(Xp_h, np.ndarray):
            raise ValueError(
                "stack_dtype='int8' quantizes dense stacks only; this "
                f"dataset builds a {type(Xp_h).__name__} sparse stack — "
                "use stack_dtype float32/bfloat16 (or auto) with sparse "
                "features"
            )
        # an int8 shard store (data/store.py) quantized at write time;
        # reuse its (q, scale) tables verbatim — requantizing the
        # dequantized row-major view would NOT be bitwise-stable
        pre = getattr(dataset, "_store_prequantized", None)
        if pre is not None:
            if pre.q.shape[:1] != (layout.n_partitions,):
                raise ValueError(
                    f"shard store holds {pre.q.shape[0]} partitions; this "
                    f"layout needs {layout.n_partitions} — rewrite the "
                    f"store with the run's partition count"
                )
            Xp_h = QuantizedStack(
                np.asarray(pre.q), np.asarray(pre.scale)
            )
        else:
            Xp_h = QuantizedStack.quantize(Xp_h)

    def _cast(leaf):
        import jax.numpy as jnp

        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating):
            return arr.astype(jnp.dtype(dtype))
        return arr

    # quantized stacks skip the float cast: the int8 payload is already
    # final and the f32 scale table must not be down-cast to a bf16 DATA
    # dtype (it scales every reconstructed value)
    _x_leaf = (lambda leaf: np.asarray(leaf)) if quantize else _cast
    put = lambda A: jax.tree.map(
        lambda leaf: put_global(_x_leaf(leaf), sharding), A
    )
    rows = yp_h.shape[1]

    Xp = yp = Xw = yw = None
    if faithful and ring:
        # ring transport shards COMPUTE by worker and DATA by partition;
        # both axes must fold onto the mesh
        mesh_lib.check_divisible(layout.n_workers, mesh, "n_workers")
        mesh_lib.check_divisible(layout.n_partitions, mesh, "n_partitions")
        Xp = put(Xp_h)
        yp = put_global(_cast(yp_h), sharding)
    elif faithful:
        mesh_lib.check_divisible(layout.n_workers, mesh, "n_workers")
        Xw_h, yw_h = worker_stack(layout, Xp_h, yp_h)
        Xw, yw = put(Xw_h), put_global(_cast(yw_h), sharding)
    else:
        mesh_lib.check_divisible(layout.n_partitions, mesh, "n_partitions")
        Xp = put(Xp_h)
        yp = put_global(_cast(yp_h), sharding)
    return ShardedData(
        Xp=Xp, yp=yp, Xw=Xw, yw=yw, n_train=rows * layout.n_partitions
    )
