"""Partitioning datasets and materializing (possibly redundant) worker stacks.

The reference shards by writing one file per partition to NFS and having each
MPI rank load its assigned (rotated/replicated) partitions at startup
(src/approximate_coding.py:39-69). Here the same assignment becomes array
indexing: a partition-major stack [P, rows, F], and — for the faithful
compute mode — a worker-major stack [W, S, rows, F] gathered through
``CodingLayout.assignment`` (the redundancy is real memory, as it was real
disk+RAM in the reference). Stacks are then device_put sharded over the
worker mesh axis.

Row-count convention matched to the reference: rows_per_partition =
n_samples // P with trailing remainder rows dropped from training
(src/coded.py:23's integer division; the remainder still appears in the
eval-replay train set there — we drop it consistently instead, documented
deviation).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import numpy as np
import scipy.sparse as sps

from erasurehead_tpu.data.synthetic import Dataset
from erasurehead_tpu.ops.codes import CodingLayout
from erasurehead_tpu.ops.features import (
    Features,
    FieldOnehot,
    PaddedRows,
    infer_field_sizes,
)
from erasurehead_tpu.parallel import mesh as mesh_lib


@dataclasses.dataclass
class ShardedData:
    """Device-resident training data for one run."""

    Xp: Features  # [P, rows, F] partition-major (deduped mode), sharded
    yp: jax.Array  # [P, rows]
    Xw: Optional[Features]  # [W, S, rows, F] worker-major (faithful), sharded
    yw: Optional[jax.Array]  # [W, S, rows]
    n_train: int  # rows actually trained on (P * rows_per_partition)


def partition_stack(dataset: Dataset, n_partitions: int, sparse_format="padded"):
    """[P, rows, F] + [P, rows] partition-major arrays (host).

    ``sparse_format`` picks the sparse stack representation (RunConfig
    docs): "padded" (PaddedRows), "fields" (FieldOnehot; raises when the
    data is not exactly-one-hot-per-field), or "auto" (fields when the
    structure allows, else padded).
    """
    n = dataset.n_samples
    rows = n // n_partitions
    if rows == 0:
        raise ValueError(f"{n} samples cannot fill {n_partitions} partitions")
    X, y = dataset.X_train, dataset.y_train
    if sps.issparse(X):
        X = X[: rows * n_partitions]
        # field structure is a whole-matrix property: infer once so every
        # partition shares the same block offsets (tables must agree)
        sizes = None
        if sparse_format in ("fields", "auto"):
            sizes = infer_field_sizes(X)
            if sizes is None and sparse_format == "fields":
                raise ValueError(
                    "sparse_format='fields' requires exactly-one-hot-per-"
                    "field data (uniform nnz/row, unit values, disjoint "
                    "ordered field blocks); use 'auto' or 'padded'"
                )
        parts = [X[i * rows : (i + 1) * rows] for i in range(n_partitions)]
        if sizes is not None:
            # from_scipy returns host numpy leaves, so this stays on host
            Xp = jax.tree.map(
                lambda *leaves: np.stack(leaves),
                *[
                    FieldOnehot.from_scipy(p, field_sizes=sizes)
                    for p in parts
                ],
            )
        else:
            nnz = max(int(np.diff(p.indptr).max()) for p in parts)
            Xp = jax.tree.map(
                lambda *leaves: np.stack(leaves),
                *[_padded_host(p, nnz) for p in parts],
            )
    else:
        if sparse_format == "fields":
            raise ValueError(
                "sparse_format='fields' requires sparse (CSR) features; "
                "this dataset is dense — use 'auto' or 'padded'"
            )
        Xp = X[: rows * n_partitions].reshape(n_partitions, rows, -1)
    yp = y[: rows * n_partitions].reshape(n_partitions, rows)
    return Xp, yp


def _padded_host(csr, nnz):
    P = PaddedRows.from_scipy(csr, nnz)
    return PaddedRows(np.asarray(P.indices), np.asarray(P.values), P.n_cols)


def worker_stack(layout: CodingLayout, Xp, yp):
    """Gather the redundant worker-major stacks through the assignment."""
    take = lambda A: (
        jax.tree.map(lambda leaf: leaf[layout.assignment], A)
        if isinstance(A, (PaddedRows, FieldOnehot))
        else A[layout.assignment]
    )
    return take(Xp), yp[layout.assignment]


def np_global(x, dtype=None):
    """np.asarray that also works in a multi-controller cluster — the
    fetch-side counterpart of :func:`put_global`.

    Cluster cases a plain np.asarray cannot handle, each needing a
    DIFFERENT collective. Every process must take the same branch, so the
    branch keys on the sharding (identical everywhere), never on this
    process's own addressability:

    - the array spans all processes but is partitioned (XLA may leave jit
      outputs sharded): process_allgather reassembles the global value;
    - the array lives on a SUBMESH that excludes some processes (an
      elastic survivor phase folded onto fewer devices): the excluded
      processes hold nothing to gather — one owning process broadcasts.
      Decidable sub-cases: a single-process submesh (its owner reads the
      whole value) or a replicated multi-process submesh (any member
      holds a full local replica); a submesh both multi-process AND
      partitioned has no single reader and is refused consistently on
      every process.
    """
    if isinstance(x, jax.Array) and jax.process_count() > 1:
        from jax.experimental import multihost_utils
        from jax.sharding import SingleDeviceSharding

        if isinstance(x.sharding, SingleDeviceSharding):
            # HOST-LOCAL array (plain device_put / fresh init): every
            # process holds its own complete copy, and the sharding is
            # NOT globally consistent (each names its own local device) —
            # keying a collective on it would make every process the
            # "owner" and a broadcast would SUM the copies. Plain local
            # read is the complete, correct value — but ONLY for a
            # host-local array: an array explicitly device_put onto one
            # specific remote device also carries SingleDeviceSharding,
            # and a non-owning process has nothing to read (ADVICE r5 #2)
            if not x.is_fully_addressable:
                raise ValueError(
                    "np_global: array has SingleDeviceSharding on a device "
                    "this process does not own — a global single-device "
                    "placement is not host-local-replicated; fetch it on "
                    "the owning process or re-shard onto a mesh sharding "
                    "before the cross-process read"
                )
            return np.asarray(x, dtype)

        procs = {d.process_index for d in x.sharding.device_set}
        me = jax.process_index()
        if len(procs) < jax.process_count():
            if len(procs) == 1:
                owner = next(iter(procs))
                val = (
                    np.asarray(x)
                    if me == owner
                    else np.zeros(x.shape, x.dtype)
                )
            elif x.is_fully_replicated:
                owner = min(procs)
                val = (
                    np.asarray(x.addressable_shards[0].data)
                    if me == owner
                    else np.zeros(x.shape, x.dtype)
                )
            else:
                # consistent refusal on EVERY process — a one-sided raise
                # would strand the others inside the broadcast collective
                raise NotImplementedError(
                    "array partitioned across a strict subset of processes"
                )
            x = multihost_utils.broadcast_one_to_all(
                val, is_source=me == owner
            )
        elif not x.is_fully_addressable:
            x = multihost_utils.process_allgather(x, tiled=True)
    return np.asarray(x, dtype)


def put_global(leaf: np.ndarray, sharding) -> jax.Array:
    """Materialize a host array as a (possibly multi-host) sharded Array.

    Single-process: plain device_put. Multi-controller (a real pod via
    jax.distributed — parallel/backend.py): every process holds the full
    host array (data prep is seeded/deterministic, the reference's NFS
    share's analogue), and each contributes only its addressable shards via
    make_array_from_callback — device_put alone cannot build an array that
    spans non-addressable devices.
    """
    if jax.process_count() == 1:
        return jax.device_put(leaf, sharding)
    # dtype must be explicit (where the jax version allows): a process can
    # own ZERO shards of this array (e.g. an elastic survivor phase folded
    # onto a 1-device mesh) and then has no shard to infer it from
    from erasurehead_tpu.utils import compat

    return compat.make_array_from_callback(
        leaf.shape, sharding, lambda idx: leaf[idx], dtype=leaf.dtype
    )


def shard_run_data(
    dataset: Dataset,
    layout: CodingLayout,
    mesh,
    faithful: bool,
    dtype=np.float32,
    sparse_format: str = "padded",
) -> ShardedData:
    """Build and device_put the stack the compute mode needs.

    Deduped mode shards partitions across devices (P % n_devices == 0);
    faithful mode shards logical workers (W % n_devices == 0) and skips the
    partition-major copy entirely (it would only waste HBM).

    ``dtype`` is the DATA dtype: float32 default; bfloat16 halves HBM
    traffic on the bandwidth-bound gradient pass (params and optimizer
    state stay float32 — trainer-side mixed precision). Integer leaves
    (PaddedRows indices) are never cast.
    """
    Xp_h, yp_h = partition_stack(
        dataset, layout.n_partitions, sparse_format=sparse_format
    )
    sharding = mesh_lib.worker_sharding(mesh)
    dtype = np.dtype(dtype) if not hasattr(dtype, "itemsize") else dtype

    def _cast(leaf):
        import jax.numpy as jnp

        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating):
            return arr.astype(jnp.dtype(dtype))
        return arr

    put = lambda A: jax.tree.map(
        lambda leaf: put_global(_cast(leaf), sharding), A
    )
    rows = yp_h.shape[1]

    Xp = yp = Xw = yw = None
    if faithful:
        mesh_lib.check_divisible(layout.n_workers, mesh, "n_workers")
        Xw_h, yw_h = worker_stack(layout, Xp_h, yp_h)
        Xw, yw = put(Xw_h), put_global(_cast(yw_h), sharding)
    else:
        mesh_lib.check_divisible(layout.n_partitions, mesh, "n_partitions")
        Xp = put(Xp_h)
        yp = put_global(_cast(yp_h), sharding)
    return ShardedData(
        Xp=Xp, yp=yp, Xw=Xw, yw=yw, n_train=rows * layout.n_partitions
    )
