"""On-disk shard store: the out-of-core home of the partition stack
(``stack_residency="streamed"``, utils/config.RunConfig).

The reference sharded by writing one file per partition to NFS and having
every MPI rank load its assignment eagerly at startup
(src/approximate_coding.py:39-69) — disk was already the partition store,
but residency was all-or-nothing. Here the store keeps that layout
(partition-major ``.npy`` shards, each holding a contiguous group of
partitions) and makes residency a *window*: the streamed trainer maps the
shards read-only (``np.load(..., mmap_mode="r")``) and materializes only
the partition window the next scan chunk needs, which data/prefetch.py
double-buffers behind the current chunk's compute.

Two store dtypes:

- ``float32`` — shards hold the source rows verbatim. A full-window read
  reassembles the training split bitwise, so :meth:`ShardStore.dataset`
  can hand the ordinary resident pipeline an identical dataset (the
  single-window fast path — streamed runs that fit stay bitwise equal to
  resident ones across every scheme/transport/stack_dtype).
- ``int8`` — partitions are quantized AT WRITE TIME through the same
  :class:`~erasurehead_tpu.ops.features.QuantizedStack` quantizer the
  resident ``stack_dtype="int8"`` path uses, so disk and PCIe bytes both
  shrink ~4x. Quantization is partition-local (per-partition scale
  tables), so the stored ``(q, scale)`` pair is identical to what a
  resident run would compute from the same source rows — streamed int8
  runs reuse the tables verbatim (requantizing a dequantized stack is NOT
  bitwise-stable; reuse is) and stay bitwise-comparable to resident int8.

Identity: the store carries the SOURCE dataset's sweep-journal content
digest in its metadata, and :meth:`ShardStore.dataset` brands rehydrated
datasets with it plus a stable ``("shard-store", digest, ...)`` cache
token — so the device-data cache (train/cache.dataset_token) and the
sweep journal (train/journal.dataset_digest) key streamed runs exactly as
they key resident ones, and a kill→resume cycle rehydrates completed rows
from the journal without touching the shards.

Writes emit ``io`` events (kind="store_write"), reads emit
``io``/"shard_read" — the byte-accounting stream behind the report's
prefetch section (obs/report.py).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from erasurehead_tpu.data.synthetic import Dataset
from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.ops.features import QuantizedStack

#: store layout version (refuse forward-incompatible directories loudly)
STORE_VERSION = 1

#: metadata file inside a store directory
META_NAME = "store_meta.json"

#: default shard payload target: groups of partitions are sized so one
#: shard file is ~this many bytes (one shard = one mmap + one sequential
#: read per visiting window edge; too small multiplies file handles, too
#: large defeats windowed reads on small stores)
SHARD_TARGET_BYTES = 64 << 20

#: store dtypes (the ON-DISK representation; the run's ``stack_dtype``
#: still governs the device representation — an f32 store feeds any of
#: them, an int8 store requires ``stack_dtype="int8"``)
STORE_DTYPES = ("float32", "int8")


def _emit_io(kind: str, n_bytes: int, **extra) -> None:
    events_lib.emit("io", kind=kind, bytes=int(n_bytes), **extra)


def partitions_per_shard(
    rows: int, n_features: int, itemsize: int, n_partitions: int
) -> int:
    """Partitions grouped into one shard file (~SHARD_TARGET_BYTES)."""
    per_part = max(1, rows * n_features * itemsize)
    return int(min(n_partitions, max(1, SHARD_TARGET_BYTES // per_part)))


def write_store(
    dataset: Dataset,
    directory: str,
    n_partitions: int,
    *,
    stack_dtype: str = "float32",
    group: Optional[int] = None,
) -> "ShardStore":
    """Shard ``dataset``'s training split into ``directory``.

    Rows follow the trainer's partition convention (sharding.
    partition_stack): rows_per_partition = n_samples // P, trailing
    remainder dropped. Dense features only — the sparse stacks stream
    through their own representations and are refused here, loudly.
    ``stack_dtype="int8"`` quantizes each partition at write time (see
    module docstring). The eval split rides along uncompressed (it is
    read once, host-side).
    """
    if stack_dtype not in STORE_DTYPES:
        raise ValueError(
            f"store stack_dtype must be one of {STORE_DTYPES}, "
            f"got {stack_dtype!r}"
        )
    X = dataset.X_train
    if not isinstance(X, np.ndarray):
        raise ValueError(
            "shard store holds dense stacks only; this dataset's "
            f"features are {type(X).__name__} — stream sparse data "
            "through its CSR artifacts (data/io.py) instead"
        )
    n = dataset.n_samples
    rows = n // n_partitions
    if rows == 0:
        raise ValueError(
            f"{n} samples cannot fill {n_partitions} partitions"
        )
    # digest the SOURCE dataset before any truncation/quantization: the
    # store inherits the identity the sweep journal would have computed
    # (deferred import: train/journal imports obs; data must stay leaf)
    from erasurehead_tpu.train import journal as journal_lib

    digest = journal_lib.dataset_digest(dataset)
    F = int(X.shape[1])
    Xp = np.ascontiguousarray(
        X[: rows * n_partitions].reshape(n_partitions, rows, F)
    )
    yp = np.ascontiguousarray(
        np.asarray(dataset.y_train)[: rows * n_partitions].reshape(
            n_partitions, rows
        )
    )
    G = int(group) if group else partitions_per_shard(
        rows, F, Xp.dtype.itemsize, n_partitions
    )
    if G < 1:
        raise ValueError(f"shard group must be >= 1, got {G}")
    os.makedirs(directory, exist_ok=True)
    quantized = stack_dtype == "int8"
    shard_parts = []
    total = 0
    for i, lo in enumerate(range(0, n_partitions, G)):
        hi = min(lo + G, n_partitions)
        block = Xp[lo:hi]
        if quantized:
            qs = QuantizedStack.quantize(block)
            np.save(os.path.join(directory, f"shard_{i:05d}.npy"), qs.q)
            np.save(os.path.join(directory, f"scale_{i:05d}.npy"), qs.scale)
            total += qs.q.nbytes + qs.scale.nbytes
        else:
            np.save(os.path.join(directory, f"shard_{i:05d}.npy"), block)
            total += block.nbytes
        np.save(os.path.join(directory, f"labels_{i:05d}.npy"), yp[lo:hi])
        total += yp[lo:hi].nbytes
        shard_parts.append(hi - lo)
    np.save(
        os.path.join(directory, "X_test.npy"), np.asarray(dataset.X_test)
    )
    np.save(
        os.path.join(directory, "y_test.npy"), np.asarray(dataset.y_test)
    )
    meta = {
        "version": STORE_VERSION,
        "name": dataset.name,
        "n_partitions": int(n_partitions),
        "rows_per_partition": int(rows),
        "n_features": F,
        "source_dtype": str(Xp.dtype),
        "label_dtype": str(yp.dtype),
        "stack_dtype": stack_dtype,
        "shard_parts": shard_parts,
        "digest": digest,
    }
    with open(os.path.join(directory, META_NAME), "w") as f:
        json.dump(meta, f, indent=1, sort_keys=True)
    _emit_io("store_write", total, path=directory, shards=len(shard_parts))
    return ShardStore(directory)


class ShardStore:
    """Read side of a shard-store directory: memory-mapped partition
    shards plus the metadata that makes streamed runs keyable.

    Shards open lazily with ``np.load(..., mmap_mode="r")`` — opening a
    store touches only the metadata, and a window read pages in only the
    rows it copies out. All reads assemble fresh (or caller-provided)
    host arrays: the mmaps never leak into device_put (a page-faulting
    transfer would serialize the prefetch pipeline behind disk).
    """

    def __init__(self, directory: str):
        self.directory = directory
        path = os.path.join(directory, META_NAME)
        with open(path) as f:
            meta = json.load(f)
        if meta.get("version") != STORE_VERSION:
            raise ValueError(
                f"{path}: store version {meta.get('version')!r} != "
                f"{STORE_VERSION} (rewrite the store with this build's "
                f"data/prepare.py)"
            )
        self.meta = meta
        self.n_partitions: int = int(meta["n_partitions"])
        self.rows_per_partition: int = int(meta["rows_per_partition"])
        self.n_features: int = int(meta["n_features"])
        self.stack_dtype: str = meta["stack_dtype"]
        self.quantized: bool = self.stack_dtype == "int8"
        self.digest: str = meta["digest"]
        #: first partition of each shard (shard s covers
        #: [starts[s], starts[s+1]))
        self._starts = np.concatenate(
            [[0], np.cumsum(meta["shard_parts"])]
        ).astype(np.int64)
        self._mmaps: dict = {}

    @property
    def cache_token(self) -> tuple:
        """Stable device-data-cache brand (train/cache.dataset_token):
        content-addressed, so two opens of one store — or a killed and a
        resumed process — key the same cached stacks."""
        return ("shard-store", self.digest, self.stack_dtype)

    def partition_bytes(self) -> int:
        """Host/PCIe bytes one partition's window slice costs (payload +
        labels + the int8 scale row — the unit serve admission and the
        auto-window resolver charge in)."""
        rows, F = self.rows_per_partition, self.n_features
        label = np.dtype(self.meta["label_dtype"]).itemsize
        if self.quantized:
            return rows * F + F * 4 + rows * label
        src = np.dtype(self.meta["source_dtype"]).itemsize
        return rows * F * src + rows * label

    def _mmap(self, prefix: str, shard: int):
        key = (prefix, shard)
        arr = self._mmaps.get(key)
        if arr is None:
            arr = np.load(
                os.path.join(self.directory, f"{prefix}_{shard:05d}.npy"),
                mmap_mode="r",
            )
            self._mmaps[key] = arr
        return arr

    def read_window(self, lo: int, hi: int, out: Optional[dict] = None):
        """Materialize partitions [lo, hi) as host arrays.

        Returns ``(X, y)`` with ``X`` a ``[hi-lo, rows, F]`` ndarray
        (f32 store) or :class:`QuantizedStack` (int8 store) and ``y``
        ``[hi-lo, rows]``. ``out`` — a dict of preallocated buffers under
        keys ``"X"``/``"y"``(/``"scale"``) — is filled in place when
        shapes match (the prefetch ring's reuse path). Emits one ``io``
        shard_read record for the bytes copied."""
        return self.read_ranges(((lo, hi),), out=out)

    def read_ranges(self, ranges, out: Optional[dict] = None):
        """Materialize a sequence of contiguous partition ranges as ONE
        stacked host window.

        ``ranges`` is a tuple of ``(lo, hi)`` pairs; the returned arrays
        concatenate them in order — the assignment-aware window planner
        (data/sharding.plan_stream_windows) uses two ranges when a
        slot-group's halo wraps the partition axis, and the staging
        order IS the plan's ring-hop order (buffer position i holds
        partition ``(window_head + i) mod P``). Same buffer-reuse and
        ``io`` accounting contract as :meth:`read_window`."""
        ranges = [(int(lo), int(hi)) for lo, hi in ranges]
        if not ranges:
            raise ValueError("read_ranges needs at least one range")
        for lo, hi in ranges:
            if not 0 <= lo < hi <= self.n_partitions:
                raise ValueError(
                    f"window [{lo}, {hi}) outside "
                    f"[0, {self.n_partitions}) partitions"
                )
        w = sum(hi - lo for lo, hi in ranges)
        rows, F = self.rows_per_partition, self.n_features
        out = out if out is not None else {}

        def buf(key, shape, dtype):
            b = out.get(key)
            if b is None or b.shape != shape or b.dtype != np.dtype(dtype):
                b = np.empty(shape, dtype)
                out[key] = b
            return b

        X = buf(
            "X", (w, rows, F),
            np.int8 if self.quantized else self.meta["source_dtype"],
        )
        y = buf("y", (w, rows), self.meta["label_dtype"])
        scale = (
            buf("scale", (w, F), np.float32) if self.quantized else None
        )
        off = 0
        for lo, hi in ranges:
            p = lo
            while p < hi:
                s = int(np.searchsorted(self._starts, p, side="right")) - 1
                blk_lo = int(self._starts[s])
                blk_hi = int(self._starts[s + 1])
                a, b = p - blk_lo, min(hi, blk_hi) - blk_lo
                dst = slice(off + p - lo, off + p - lo + (b - a))
                X[dst] = self._mmap("shard", s)[a:b]
                y[dst] = self._mmap("labels", s)[a:b]
                if scale is not None:
                    scale[dst] = self._mmap("scale", s)[a:b]
                p += b - a
            off += hi - lo
        n_bytes = X.nbytes + y.nbytes + (
            scale.nbytes if scale is not None else 0
        )
        _emit_io(
            "shard_read",
            n_bytes,
            partitions=[int(r[0]) for r in ranges[:1]]
            + [int(ranges[0][1])],
            ranges=[[int(lo), int(hi)] for lo, hi in ranges],
        )
        if self.quantized:
            return QuantizedStack(X, scale), y
        return X, y

    def eval_split(self):
        """The uncompressed eval split (read eagerly; it is small and
        host-side)."""
        X_test = np.load(os.path.join(self.directory, "X_test.npy"))
        y_test = np.load(os.path.join(self.directory, "y_test.npy"))
        return X_test, y_test

    def dataset(self) -> Dataset:
        """Rehydrate a resident-equivalent Dataset (the single-window
        fast path: a streamed run whose window covers every partition is
        the resident run, so the trainer swaps this in and takes the
        ordinary pipeline — bitwise-identically for an f32 store).

        An int8 store dequantizes for the row-major view but ALSO brands
        the object with the stored stack (``_store_prequantized``) so
        sharding.shard_run_data reuses the write-time ``(q, scale)``
        tables instead of requantizing the reconstruction (which would
        not be bitwise-stable). Branded with the source digest and a
        content-addressed cache token, so journal and device-data-cache
        keys match runs over the original dataset."""
        P, rows = self.n_partitions, self.rows_per_partition
        X, y = self.read_window(0, P)
        pre = None
        if self.quantized:
            pre = X
            X = np.asarray(pre.dequantize())
        X_test, y_test = self.eval_split()
        ds = Dataset(
            X_train=np.ascontiguousarray(X.reshape(P * rows, -1)),
            y_train=np.ascontiguousarray(y.reshape(P * rows)),
            X_test=X_test,
            y_test=y_test,
            name=self.meta.get("name", "shard-store"),
        )
        ds._sweep_journal_digest = self.digest
        ds._sweep_cache_token = self.cache_token
        ds._shard_store = self
        if pre is not None:
            ds._store_prequantized = pre
        return ds

    def close(self) -> None:
        self._mmaps.clear()


def open_store(directory: str) -> ShardStore:
    """Open an existing store directory (loud when absent)."""
    if not os.path.exists(os.path.join(directory, META_NAME)):
        raise FileNotFoundError(
            f"{directory!r} is not a shard store (no {META_NAME}; write "
            f"one with `python -m erasurehead_tpu.data.prepare ... "
            f"--store DIR`)"
        )
    return ShardStore(directory)
