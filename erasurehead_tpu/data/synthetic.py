"""Synthetic two-component GMM dataset (the reference's "artificial data").

Distribution matched to src/generate_data.py:8-46 + src/util.py:39-47:
  - a ground-truth beta* with iid +-1 entries,
  - class means mu = +-(1.5 / n_cols) * beta*,
  - features: per-partition, a Binomial(rows, 1/2) split between the two
    components, each row mu_c + (10/sqrt(n_cols)) * N(0, I) — component-1
    rows stacked before component-2 rows, unshuffled, exactly like the
    reference's generate_random_matrix_normal (src/util.py:39-43),
  - labels drawn from the true logistic model: y = 2*Bernoulli(sigmoid(X
    beta*)) - 1 (src/generate_data.py:34-35),
  - a test split of 0.2 * n_rows generated the same way
    (src/generate_data.py:41-43).

Deviation: the reference's generator is unseeded (its np.random.seed(0) is
commented out, src/generate_data.py:54); we seed for reproducibility.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class Dataset:
    """In-memory dataset, row-major with partition-contiguous training rows."""

    X_train: np.ndarray | object  # [n, F] dense ndarray or scipy CSR
    y_train: np.ndarray  # [n] in {-1, +1} (or real-valued for regression)
    X_test: np.ndarray | object
    y_test: np.ndarray
    name: str = "artificial"

    @property
    def n_samples(self) -> int:
        return self.X_train.shape[0]

    @property
    def n_features(self) -> int:
        return self.X_train.shape[1]


def _gmm_block(
    rng: np.random.Generator, mu1, mu2, n_rows: int, n_cols: int
) -> np.ndarray:
    n2 = rng.binomial(n_rows, 0.5)
    n1 = n_rows - n2
    scale = 10.0 / np.sqrt(n_cols)
    return np.concatenate(
        [
            mu1 + scale * rng.standard_normal((n1, n_cols)),
            mu2 + scale * rng.standard_normal((n2, n_cols)),
        ]
    )


def generate_gmm(
    n_rows: int,
    n_cols: int,
    n_partitions: int,
    seed: int = 0,
    dtype=np.float32,
) -> Dataset:
    """Generate the reference's synthetic logistic-regression task.

    Rows are generated per-partition (partition i occupying the contiguous
    row block i) so partition boundaries match the reference's per-partition
    files; n_rows must be a multiple of n_partitions
    (src/generate_data.py:11).
    """
    if n_rows % n_partitions:
        raise ValueError("n_rows must be a multiple of n_partitions")
    rng = np.random.default_rng(seed)
    beta_true = rng.integers(0, 2, n_cols) * 2.0 - 1.0
    mu1 = (1.5 / n_cols) * beta_true
    mu2 = -mu1
    rows_per = n_rows // n_partitions

    def labeled_block(n):
        X = _gmm_block(rng, mu1, mu2, n, n_cols)
        p = 1.0 / (1.0 + np.exp(-X @ beta_true))
        y = 2.0 * rng.binomial(1, p) - 1.0
        return X.astype(dtype), y.astype(dtype)

    blocks = [labeled_block(rows_per) for _ in range(n_partitions)]
    X_train = np.concatenate([b[0] for b in blocks])
    y_train = np.concatenate([b[1] for b in blocks])
    X_test, y_test = labeled_block(int(0.2 * n_rows))
    return Dataset(X_train, y_train, X_test, y_test, name="artificial")


def generate_linear(
    n_rows: int,
    n_cols: int,
    n_partitions: int,
    seed: int = 0,
    noise: float = 0.1,
    dtype=np.float32,
) -> Dataset:
    """Synthetic least-squares task (regression counterpart, same geometry)."""
    if n_rows % n_partitions:
        raise ValueError("n_rows must be a multiple of n_partitions")
    rng = np.random.default_rng(seed)
    beta_true = rng.standard_normal(n_cols) / np.sqrt(n_cols)
    def block(n):
        X = rng.standard_normal((n, n_cols)) / np.sqrt(n_cols)
        y = X @ beta_true + noise * rng.standard_normal(n)
        return X.astype(dtype), y.astype(dtype)
    X_train, y_train = block(n_rows)
    X_test, y_test = block(int(0.2 * n_rows))
    return Dataset(X_train, y_train, X_test, y_test, name="artificial-linear")
