"""Synthetic two-component GMM dataset (the reference's "artificial data").

Distribution matched to src/generate_data.py:8-46 + src/util.py:39-47:
  - a ground-truth beta* with iid +-1 entries,
  - class means mu = +-(1.5 / n_cols) * beta*,
  - features: per-partition, a Binomial(rows, 1/2) split between the two
    components, each row mu_c + (10/sqrt(n_cols)) * N(0, I) — component-1
    rows stacked before component-2 rows, unshuffled, exactly like the
    reference's generate_random_matrix_normal (src/util.py:39-43),
  - labels drawn from the true logistic model: y = 2*Bernoulli(sigmoid(X
    beta*)) - 1 (src/generate_data.py:34-35),
  - a test split of 0.2 * n_rows generated the same way
    (src/generate_data.py:41-43).

Deviation: the reference's generator is unseeded (its np.random.seed(0) is
commented out, src/generate_data.py:54); we seed for reproducibility.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class Dataset:
    """In-memory dataset, row-major with partition-contiguous training rows."""

    X_train: np.ndarray | object  # [n, F] dense ndarray or scipy CSR
    y_train: np.ndarray  # [n] in {-1, +1} (or real-valued for regression)
    X_test: np.ndarray | object
    y_test: np.ndarray
    name: str = "artificial"

    @property
    def n_samples(self) -> int:
        return self.X_train.shape[0]

    @property
    def n_features(self) -> int:
        return self.X_train.shape[1]


def _gmm_block(
    rng: np.random.Generator, mu1, mu2, n_rows: int, n_cols: int
) -> np.ndarray:
    n2 = rng.binomial(n_rows, 0.5)
    n1 = n_rows - n2
    scale = 10.0 / np.sqrt(n_cols)
    return np.concatenate(
        [
            mu1 + scale * rng.standard_normal((n1, n_cols)),
            mu2 + scale * rng.standard_normal((n2, n_cols)),
        ]
    )


def generate_gmm(
    n_rows: int,
    n_cols: int,
    n_partitions: int,
    seed: int = 0,
    dtype=np.float32,
) -> Dataset:
    """Generate the reference's synthetic logistic-regression task.

    Rows are generated per-partition (partition i occupying the contiguous
    row block i) so partition boundaries match the reference's per-partition
    files; n_rows must be a multiple of n_partitions
    (src/generate_data.py:11).
    """
    if n_rows % n_partitions:
        raise ValueError("n_rows must be a multiple of n_partitions")
    rng = np.random.default_rng(seed)
    beta_true = rng.integers(0, 2, n_cols) * 2.0 - 1.0
    mu1 = (1.5 / n_cols) * beta_true
    mu2 = -mu1
    rows_per = n_rows // n_partitions

    def labeled_block(n):
        X = _gmm_block(rng, mu1, mu2, n, n_cols)
        p = 1.0 / (1.0 + np.exp(-X @ beta_true))
        y = 2.0 * rng.binomial(1, p) - 1.0
        return X.astype(dtype), y.astype(dtype)

    blocks = [labeled_block(rows_per) for _ in range(n_partitions)]
    X_train = np.concatenate([b[0] for b in blocks])
    y_train = np.concatenate([b[1] for b in blocks])
    X_test, y_test = labeled_block(int(0.2 * n_rows))
    return Dataset(X_train, y_train, X_test, y_test, name="artificial")


def generate_onehot(
    n_rows: int,
    n_cols: int,
    n_partitions: int,
    n_fields: int = 12,
    seed: int = 0,
) -> Dataset:
    """Covtype-style sparse one-hot logistic task (scipy CSR features).

    The reference's flagship real workloads are one-hot sparse CSR matrices
    (src/arrange_real_data.py:145-205 bins covtype's columns into 15509
    one-hot categories; amazon hashes to 241915). This generator produces a
    synthetic task with the identical *structure*: ``n_fields`` categorical
    fields, each row activating exactly one category per field (value 1.0,
    so nnz_per_row == n_fields), labels drawn from a true logistic model
    over the one-hot features — sized by the caller to the canonical shapes
    so the PaddedRows gather/scatter path can be exercised and timed at
    reference scale without the Kaggle raws (absent in this environment).
    """
    import scipy.sparse as sps

    if n_rows % n_partitions:
        raise ValueError("n_rows must be a multiple of n_partitions")
    if n_fields > n_cols:
        raise ValueError("n_fields cannot exceed n_cols")
    rng = np.random.default_rng(seed)
    # contiguous category blocks per field (last absorbs the remainder),
    # mirroring one-hot encoder column layout
    bounds = np.linspace(0, n_cols, n_fields + 1).astype(np.int64)
    # unit logit variance: sum of n_fields iid N(0, 1/n_fields) entries
    beta_true = rng.standard_normal(n_cols) / np.sqrt(n_fields)

    def block(n):
        cats = rng.random((n, n_fields))
        lo, hi = bounds[:-1], bounds[1:]
        idx = (lo + (cats * (hi - lo)).astype(np.int64)).astype(np.int32)
        logits = beta_true[idx].sum(axis=1)
        y = (2.0 * rng.binomial(1, 1.0 / (1.0 + np.exp(-logits))) - 1.0)
        X = sps.csr_matrix(
            (
                np.ones(n * n_fields, dtype=np.float32),
                idx.ravel(),
                np.arange(n + 1, dtype=np.int64) * n_fields,
            ),
            shape=(n, n_cols),
        )
        return X, y.astype(np.float32)

    X_train, y_train = block(n_rows)
    X_test, y_test = block(int(0.2 * n_rows))
    return Dataset(X_train, y_train, X_test, y_test, name="artificial-onehot")


def generate_linear(
    n_rows: int,
    n_cols: int,
    n_partitions: int,
    seed: int = 0,
    noise: float = 0.1,
    dtype=np.float32,
) -> Dataset:
    """Synthetic least-squares task (regression counterpart, same geometry)."""
    if n_rows % n_partitions:
        raise ValueError("n_rows must be a multiple of n_partitions")
    rng = np.random.default_rng(seed)
    beta_true = rng.standard_normal(n_cols) / np.sqrt(n_cols)
    def block(n):
        X = rng.standard_normal((n, n_cols)) / np.sqrt(n_cols)
        y = X @ beta_true + noise * rng.standard_normal(n)
        return X.astype(dtype), y.astype(dtype)
    X_train, y_train = block(n_rows)
    X_test, y_test = block(int(0.2 * n_rows))
    return Dataset(X_train, y_train, X_test, y_test, name="artificial-linear")
