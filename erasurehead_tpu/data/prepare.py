"""Dataset preparation CLI: the reference's generate_data.py /
arrange_real_data.py / data_prepare.sh rolled into one entry point.

Synthetic (reference: ``make generate_random_data`` -> generate_data.py)::

    python -m erasurehead_tpu.data.prepare synthetic --rows 4096 --cols 100 \\
        --workers 30 --out ./straggdata

Real (reference: data_prepare.sh -> arrange_real_data.py)::

    python -m erasurehead_tpu.data.prepare real --dataset kc_house_data \\
        --source ./straggdata/kc_house_data --workers 30 --out ./straggdata

Both write the reference's on-disk layout (per-partition files + labels +
test split) under the reference's directory naming
(generate_data.py:59-62, arrange_real_data.py:71-77), so prepared data is
interchangeable between the two frameworks. ``--partial`` mirrors the
partial-schemes partition count (n_procs-1)*(n_partitions-n_stragglers).

``--store DIR`` additionally writes an out-of-core shard store
(data/store.py) — the input ``stack_residency="streamed"`` runs open
instead of loading the whole training split; ``--store-dtype int8``
quantizes at write time (~4x smaller disk and PCIe bytes).
"""

from __future__ import annotations

import argparse
import os
import sys

from erasurehead_tpu.data import io as data_io
from erasurehead_tpu.data import real as real_data
from erasurehead_tpu.data.synthetic import generate_gmm


def _n_partitions(ns) -> int:
    if ns.partial:
        return ns.workers * (ns.partitions_per_worker - ns.stragglers)
    return ns.workers


def _leaf(ns) -> str:
    return (
        f"partial/{_n_partitions(ns)}" if ns.partial else str(ns.workers)
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="erasurehead-tpu-prepare")
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("synthetic", help="generate the GMM logistic task")
    ps.add_argument("--rows", type=int, default=4096)
    ps.add_argument("--cols", type=int, default=100)
    ps.add_argument("--seed", type=int, default=0)

    pr = sub.add_parser("real", help="preprocess a real dataset")
    pr.add_argument("--dataset", required=True, choices=sorted(real_data.PREPARERS))
    pr.add_argument("--source", required=True, help="dir with the raw files")

    for q in (ps, pr):
        q.add_argument("--workers", type=int, default=30)
        q.add_argument("--out", default="./straggdata")
        q.add_argument("--partial", action="store_true")
        q.add_argument("--stragglers", type=int, default=0)
        q.add_argument("--partitions-per-worker", type=int, default=0)
        q.add_argument(
            "--store",
            default=None,
            metavar="DIR",
            help="ALSO write an out-of-core shard store (data/store.py) "
            "here — the stack_residency=streamed input",
        )
        q.add_argument(
            "--store-dtype",
            default="float32",
            choices=("float32", "int8"),
            help="on-disk shard dtype: int8 quantizes partitions at "
            "write time (~4x smaller disk + PCIe; requires the run to "
            "use stack_dtype=int8)",
        )

    ns = p.parse_args(argv)
    if ns.partial and ns.partitions_per_worker < ns.stragglers + 2:
        p.error(
            "--partial needs --partitions-per-worker >= --stragglers + 2 "
            f"(got {ns.partitions_per_worker} vs s={ns.stragglers})"
        )
    parts = _n_partitions(ns)

    if ns.cmd == "synthetic":
        ds = generate_gmm(ns.rows, ns.cols, parts, seed=ns.seed)
        out = os.path.join(
            ns.out, f"artificial-data/{ns.rows}x{ns.cols}", _leaf(ns)
        )
    else:
        ds = real_data.prepare(ns.dataset, ns.source)
        out = os.path.join(ns.out, ns.dataset, _leaf(ns))

    data_io.write_reference_layout(ds, out, parts)
    rows = ds.n_samples // parts
    print(
        f"wrote {parts} partitions x {rows} rows "
        f"({ds.n_samples} train, {ds.X_test.shape[0]} test, "
        f"{ds.n_features} features) -> {out}"
    )
    if ns.store:
        from erasurehead_tpu.data import store as store_lib

        st = store_lib.write_store(
            ds, ns.store, parts, stack_dtype=ns.store_dtype
        )
        print(
            f"wrote shard store ({ns.store_dtype}, "
            f"{len(st.meta['shard_parts'])} shards, digest {st.digest}) "
            f"-> {ns.store}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
