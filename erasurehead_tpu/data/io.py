"""On-disk dataset formats: reference-compatible text/.npz plus fast .npy.

The reference's data layer stores each partition as either a dense
whitespace text matrix ``<i>.dat`` loaded with np.loadtxt (src/util.py:13-15,
26-36) or a sparse CSR ``<i>.npz`` (src/util.py:17-24), with ``label.dat`` /
``test_data[.dat|.npz]`` / ``label_test.dat`` alongside
(src/generate_data.py:29-46). We read and write that exact layout (so data
prepared for the reference drops in unchanged) and additionally cache a
``.npy`` mirror — text parsing 400k-row matrices with loadtxt is minutes;
np.load is milliseconds.
"""

from __future__ import annotations

import os

import numpy as np
import scipy.sparse as sps

from erasurehead_tpu.data.synthetic import Dataset


#: The reference's label writer truncates every value to three decimals
#: ("%5.3f", src/util.py:32-36) — label files written BY the reference
#: carry that precision loss, and our loaders must tolerate the form
#: (pinned in tests/test_data.py). We default to full precision instead;
#: pass ``fmt=REFERENCE_LABEL_FMT`` to write byte-compatible files.
REFERENCE_LABEL_FMT = "%5.3f"


def save_dense_text(path: str, m: np.ndarray, fmt: str = "%.18g") -> None:
    """Whitespace text matrix, reference format (src/util.py:26-30)."""
    np.savetxt(path, np.atleast_2d(m), fmt=fmt)


def load_dense_text(path: str, mmap: bool = True) -> np.ndarray:
    """Dense text matrix with a .npy cache sidecar.

    Cold loads go through the native from_chars parser (data/native,
    measured ~7x np.loadtxt on the 54000x100 reference shape) when the
    toolchain is available, np.loadtxt otherwise; both produce identical
    arrays (pinned in test_native).

    Warm loads memory-map the .npy cache read-only (``mmap=True``, the
    default) instead of materializing the full array eagerly: partitions
    a run never touches never leave the page cache, which is what lets
    the out-of-core path open a reference layout without paying its full
    host footprint. Values are bitwise-identical either way (np.load
    semantics; pinned in tests) — pass ``mmap=False`` for a private
    writable copy."""
    cache = path + ".npy"
    if os.path.exists(cache) and os.path.getmtime(cache) >= os.path.getmtime(path):
        return np.load(cache, mmap_mode="r" if mmap else None)
    from erasurehead_tpu.data import native

    m = native.load_dense_text_native(path)
    if m is None:
        m = np.loadtxt(path, dtype=np.float64)
    try:
        np.save(cache, m)
    except OSError:
        pass  # read-only data dir: degrade to plain text parsing
    return m


def save_csr(path_no_ext: str, m) -> None:
    """Reference .npz CSR layout (src/util.py:17-19)."""
    m = m.tocsr()
    np.savez(
        path_no_ext,
        data=m.data,
        indices=m.indices,
        indptr=m.indptr,
        shape=m.shape,
    )


def load_csr(path_no_ext: str):
    """Reference .npz CSR loader (src/util.py:21-24)."""
    with np.load(path_no_ext + ".npz") as z:
        return sps.csr_matrix(
            (z["data"], z["indices"], z["indptr"]), shape=z["shape"]
        )


def write_reference_layout(
    dataset: Dataset, out_dir: str, n_partitions: int
) -> None:
    """Write a dataset in the reference's per-partition directory layout
    (src/generate_data.py:29-46): ``<i>.dat``/``<i>.npz`` (1-based),
    label.dat, test_data[.dat], label_test.dat."""
    os.makedirs(out_dir, exist_ok=True)
    n = dataset.n_samples
    rows = n // n_partitions
    sparse = sps.issparse(dataset.X_train)
    for i in range(n_partitions):
        block = dataset.X_train[i * rows : (i + 1) * rows]
        if sparse:
            save_csr(os.path.join(out_dir, str(i + 1)), block)
        else:
            save_dense_text(os.path.join(out_dir, f"{i + 1}.dat"), block)
    save_dense_text(
        os.path.join(out_dir, "label.dat"), dataset.y_train[: rows * n_partitions]
    )
    if sparse:
        save_csr(os.path.join(out_dir, "test_data"), dataset.X_test)
    else:
        save_dense_text(os.path.join(out_dir, "test_data.dat"), dataset.X_test)
    save_dense_text(os.path.join(out_dir, "label_test.dat"), dataset.y_test)


def has_reference_layout(path: str | None) -> bool:
    """True iff ``path`` holds at least partition 1 of a reference layout.

    Checking for the partition file, not just the directory: artifact
    writes create ``<dir>/results/`` and must not make a dataset dir look
    loadable."""
    return path is not None and (
        os.path.exists(os.path.join(path, "1.dat"))
        or os.path.exists(os.path.join(path, "1.npz"))
    )


def layout_is_sparse(path: str) -> bool:
    """Whether a reference-layout directory stores CSR (.npz) partitions."""
    return os.path.exists(os.path.join(path, "1.npz"))


def read_reference_layout(
    in_dir: str, n_partitions: int, sparse: bool | None = None
) -> Dataset:
    """Load a reference-layout directory back into a Dataset.

    ``sparse=None`` autodetects from which partition-1 file exists — callers
    guessing wrong (e.g. assuming real datasets are always CSR when the
    preparer wrote dense text) would otherwise crash on np.load."""
    if sparse is None:
        sparse = layout_is_sparse(in_dir)
    parts = []
    for i in range(n_partitions):
        if sparse:
            parts.append(load_csr(os.path.join(in_dir, str(i + 1))))
        else:
            parts.append(load_dense_text(os.path.join(in_dir, f"{i + 1}.dat")))
    X_train = sps.vstack(parts).tocsr() if sparse else np.vstack(parts)
    y_train = load_dense_text(os.path.join(in_dir, "label.dat")).reshape(-1)
    if sparse:
        X_test = load_csr(os.path.join(in_dir, "test_data"))
    else:
        X_test = load_dense_text(os.path.join(in_dir, "test_data.dat"))
    y_test = load_dense_text(os.path.join(in_dir, "label_test.dat")).reshape(-1)
    return Dataset(
        X_train=X_train,
        y_train=y_train[: X_train.shape[0]],
        X_test=X_test,
        y_test=y_test,
        name=os.path.basename(os.path.normpath(in_dir)),
    )
