"""ctypes binding for the native text-matrix parser (loadtxt.cpp).

Build-on-first-use: the shared object is compiled next to the source with
g++ and cached; any failure (no toolchain, parse error, weird file) makes
:func:`load_dense_text_native` return None and the caller (data/io.py)
falls back to np.loadtxt. The native path is a pure accelerator — never a
correctness dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "loadtxt.cpp")
_SO = os.path.join(_DIR, "_loadtxt.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _compile() -> str:
    if not (
        os.path.exists(_SO)
        and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
    ):
        # build to a per-pid temp name + atomic rename: concurrent test
        # processes must never dlopen a half-written .so
        tmp = f"{_SO}.{os.getpid()}.tmp"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, _SRC],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, _SO)
    return _SO


def get_lib() -> Optional[ctypes.CDLL]:
    """The compiled library, or None if the toolchain is unavailable."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            lib = ctypes.CDLL(_compile())
        except Exception:
            _build_failed = True
            return None
        lib.eh_parse_alloc.restype = ctypes.POINTER(ctypes.c_double)
        lib.eh_parse_alloc.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
        ]
        lib.eh_free.restype = None
        lib.eh_free.argtypes = [ctypes.POINTER(ctypes.c_double)]
        _lib = lib
        return _lib


def load_dense_text_native(path: str) -> Optional[np.ndarray]:
    """np.loadtxt-compatible parse of a dense text matrix, or None.

    Matches np.loadtxt's squeeze semantics for the shapes the reference
    writes (R x C matrices and label vectors): a single-row or
    single-column file comes back 1-D.
    """
    lib = get_lib()
    if lib is None:
        return None
    n_vals = ctypes.c_long()
    n_rows = ctypes.c_long()
    ptr = lib.eh_parse_alloc(
        os.fsencode(path), ctypes.byref(n_vals), ctypes.byref(n_rows)
    )
    if not ptr:
        return None  # io/parse error: let np.loadtxt decide / report
    try:
        n, rows = n_vals.value, n_rows.value
        if n <= 0 or rows <= 0 or n % rows != 0:
            return None  # empty or ragged: np.loadtxt's message is better
        out = np.ctypeslib.as_array(ptr, shape=(n,)).copy()
    finally:
        lib.eh_free(ptr)
    m = out.reshape(rows, n // rows)
    if m.shape == (1, 1):
        return m.reshape(())  # np.loadtxt yields a 0-d array for a 1x1 file
    if m.shape[0] == 1:
        return m[0]
    if m.shape[1] == 1:
        return m[:, 0]
    return m
