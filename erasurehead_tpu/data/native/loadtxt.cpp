// Fast parser for the reference's whitespace-text matrix format
// (src/util.py:13-15, 26-36: dense .dat files written row-per-line and
// read back with np.loadtxt). A single-pass std::from_chars scan measures
// ~7x np.loadtxt's tokenizer on the reference's 54000x100 synthetic shape
// (0.36s vs 2.6s cold).
//
// Exposed C ABI (ctypes, see data/native/__init__.py):
//   eh_parse_alloc(path, &n_vals, &n_rows): single-pass parse into a
//     malloc'd buffer (nullptr on error; code in n_vals: -1 io, -2 token).
//   eh_free(buf): release that buffer.
//
// Single malloc'd read of the whole file, then one from_chars pass. Matches
// np.loadtxt semantics for well-formed numeric matrices (incl. exponents,
// +/-inf, nan); ragged or non-numeric files report an error and the Python
// caller falls back to np.loadtxt.

#include <cctype>
#include <charconv>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

char* read_all(const char* path, long* len) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  char* buf = static_cast<char*>(std::malloc(n + 1));
  if (!buf) {
    std::fclose(f);
    return nullptr;
  }
  long got = static_cast<long>(std::fread(buf, 1, n, f));
  std::fclose(f);
  if (got != n) {
    std::free(buf);
    return nullptr;
  }
  buf[n] = '\0';
  *len = n;
  return buf;
}

}  // namespace

extern "C" {

// Single-pass parse: returns a malloc'd value buffer (caller frees with
// eh_free), sets *n_vals and *n_rows. nullptr on error with the code in
// *n_vals (-1 io, -2 bad token). Rows = lines containing >= 1 token.
double* eh_parse_alloc(const char* path, long* n_vals, long* n_rows) {
  long len = 0;
  char* buf = read_all(path, &len);
  *n_vals = -1;
  *n_rows = 0;
  if (!buf) return nullptr;
  long cap = 1024;
  long n = 0, rows = 0;
  double* out = static_cast<double*>(std::malloc(cap * sizeof(double)));
  if (!out) {
    std::free(buf);
    return nullptr;
  }
  const char* p = buf;
  const char* end = buf + len;
  bool line_has_token = false;
  while (true) {
    while (p < end && std::isspace(static_cast<unsigned char>(*p))) {
      if (*p == '\n' && line_has_token) {
        ++rows;
        line_has_token = false;
      }
      ++p;
    }
    if (p >= end) break;
    double v;
    auto res = std::from_chars(p, end, v);
    const char* q = res.ptr;
    if (res.ec != std::errc() || q == p) {
      char* q2 = nullptr;
      v = std::strtod(p, &q2);
      if (q2 == p) {
        std::free(buf);
        std::free(out);
        *n_vals = -2;
        return nullptr;
      }
      q = q2;
    }
    if (n >= cap) {
      cap *= 2;
      double* grown =
          static_cast<double*>(std::realloc(out, cap * sizeof(double)));
      if (!grown) {
        std::free(buf);
        std::free(out);
        return nullptr;
      }
      out = grown;
    }
    out[n++] = v;
    line_has_token = true;
    p = q;
  }
  if (line_has_token) ++rows;  // final line without trailing newline
  std::free(buf);
  *n_vals = n;
  *n_rows = rows;
  return out;
}

void eh_free(double* p) { std::free(p); }

}  // extern "C"
