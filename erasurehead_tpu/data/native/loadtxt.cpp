// Fast parser for the reference's whitespace-text matrix format
// (src/util.py:13-15, 26-36: dense .dat files written row-per-line and
// read back with np.loadtxt). A single-pass std::from_chars scan measures
// ~7x np.loadtxt's tokenizer on the reference's 54000x100 synthetic shape
// (0.36s vs 2.6s cold).
//
// Exposed C ABI (ctypes, see data/native/__init__.py):
//   eh_parse(path, out, cap): parse every token; out==nullptr counts only.
//     Returns token count, or <0 on error (-1 io, -2 bad token, -3 cap).
//   eh_rows(path): number of lines containing at least one token.
//
// Single malloc'd read of the whole file, then one strtod pass. Matches
// np.loadtxt semantics for well-formed numeric matrices (incl. exponents,
// +/-inf, nan); ragged or non-numeric files report an error and the Python
// caller falls back to np.loadtxt.

#include <cctype>
#include <charconv>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

char* read_all(const char* path, long* len) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  char* buf = static_cast<char*>(std::malloc(n + 1));
  if (!buf) {
    std::fclose(f);
    return nullptr;
  }
  long got = static_cast<long>(std::fread(buf, 1, n, f));
  std::fclose(f);
  if (got != n) {
    std::free(buf);
    return nullptr;
  }
  buf[n] = '\0';
  *len = n;
  return buf;
}

}  // namespace

extern "C" {

// Single-pass parse: returns a malloc'd value buffer (caller frees with
// eh_free), sets *n_vals and *n_rows. nullptr on error with the code in
// *n_vals (-1 io, -2 bad token). Rows = lines containing >= 1 token.
double* eh_parse_alloc(const char* path, long* n_vals, long* n_rows) {
  long len = 0;
  char* buf = read_all(path, &len);
  *n_vals = -1;
  *n_rows = 0;
  if (!buf) return nullptr;
  long cap = 1024;
  long n = 0, rows = 0;
  double* out = static_cast<double*>(std::malloc(cap * sizeof(double)));
  if (!out) {
    std::free(buf);
    return nullptr;
  }
  const char* p = buf;
  const char* end = buf + len;
  bool line_has_token = false;
  while (true) {
    while (p < end && std::isspace(static_cast<unsigned char>(*p))) {
      if (*p == '\n' && line_has_token) {
        ++rows;
        line_has_token = false;
      }
      ++p;
    }
    if (p >= end) break;
    double v;
    auto res = std::from_chars(p, end, v);
    const char* q = res.ptr;
    if (res.ec != std::errc() || q == p) {
      char* q2 = nullptr;
      v = std::strtod(p, &q2);
      if (q2 == p) {
        std::free(buf);
        std::free(out);
        *n_vals = -2;
        return nullptr;
      }
      q = q2;
    }
    if (n >= cap) {
      cap *= 2;
      double* grown =
          static_cast<double*>(std::realloc(out, cap * sizeof(double)));
      if (!grown) {
        std::free(buf);
        std::free(out);
        return nullptr;
      }
      out = grown;
    }
    out[n++] = v;
    line_has_token = true;
    p = q;
  }
  if (line_has_token) ++rows;  // final line without trailing newline
  std::free(buf);
  *n_vals = n;
  *n_rows = rows;
  return out;
}

void eh_free(double* p) { std::free(p); }

long eh_parse(const char* path, double* out, long cap) {
  long len = 0;
  char* buf = read_all(path, &len);
  if (!buf) return -1;
  long n = 0;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    while (p < end && std::isspace(static_cast<unsigned char>(*p))) ++p;
    if (p >= end) break;
    double v;
    // std::from_chars: locale-free, ~3-4x strtod. It rejects a leading
    // '+' and the inf/nan spellings np.savetxt emits, so fall back to
    // strtod for any token it refuses.
    auto res = std::from_chars(p, end, v);
    const char* q = res.ptr;
    if (res.ec != std::errc() || q == p) {
      char* q2 = nullptr;
      v = std::strtod(p, &q2);
      if (q2 == p) {
        std::free(buf);
        return -2;  // non-numeric token: caller falls back to np.loadtxt
      }
      q = q2;
    }
    if (out) {
      if (n >= cap) {
        std::free(buf);
        return -3;
      }
      out[n] = v;
    }
    ++n;
    p = q;
  }
  std::free(buf);
  return n;
}

long eh_rows(const char* path) {
  long len = 0;
  char* buf = read_all(path, &len);
  if (!buf) return -1;
  long rows = 0;
  bool line_has_token = false;
  for (const char* p = buf; ; ++p) {
    if (*p == '\n' || *p == '\0') {
      if (line_has_token) ++rows;
      line_has_token = false;
      if (*p == '\0') break;
    } else if (!std::isspace(static_cast<unsigned char>(*p))) {
      line_has_token = true;
    }
  }
  std::free(buf);
  return rows;
}

}  // extern "C"
