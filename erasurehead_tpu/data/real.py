"""Real-dataset preprocessing: amazon, dna, covtype, kc_house_data.

Re-implements the four dataset branches of the reference's
src/arrange_real_data.py as one shared pipeline (each reference branch
repeats the same skeleton: featurize -> bias column -> 80/20 split with
random_state=0 -> one-hot encode (fit on train+test) -> sparse CSR
partitions):

  amazon  (arrange_real_data.py:34-91):  Kaggle amazon-employee-access
      train.csv; per-column label encoding, degree-2 hashed interaction
      terms excluding column pairs (5,7) and (2,3)
      (util.py:49-55), re-encoding, bias column.
  dna     (arrange_real_data.py:93-143): first 500k rows of features.csv;
      col 0 is the label; bias column scaled 1/sqrt(n).
  covtype (arrange_real_data.py:145-205): sklearn fetch_covtype, classes
      {1,2} kept and mapped to {-1,+1}, per-column label encoding, bias.
  kc_house_data (arrange_real_data.py:207-253): kc_house_data.csv,
      'bedrooms' onward as features, bias, price/1e6 as regression target.

Determinism matches the reference: np.random.seed(0)
(arrange_real_data.py:27) and train_test_split(random_state=0).

Zero-egress note: all loaders work from local files; ``covtype`` also
accepts sklearn's cached fetch_covtype when the cache exists. Missing
sources raise with download instructions rather than fetching.
"""

from __future__ import annotations

import itertools
import math
import os
from typing import Callable, Optional

import numpy as np

from erasurehead_tpu.data.synthetic import Dataset

#: column pairs excluded from amazon interaction features (util.py:53:
#: ROLE_CODEs pair and the two ROLE_ROLLUPs pair)
AMAZON_EXCLUDED_PAIRS = ((5, 7), (2, 3))


def _label_encode_columns(X: np.ndarray) -> np.ndarray:
    """Map each column's values onto 0..n_unique-1 (order-preserving), the
    effect of the reference's per-column LabelEncoder loop
    (arrange_real_data.py:41-44)."""
    out = np.empty_like(X, dtype=np.int64)
    for col in range(X.shape[1]):
        _, inverse = np.unique(X[:, col], return_inverse=True)
        out[:, col] = inverse
    return out


def hashed_interactions(
    X: np.ndarray, degree: int = 2, excluded_pairs=AMAZON_EXCLUDED_PAIRS
) -> np.ndarray:
    """Degree-d interaction features by hashing value tuples (util.py:49-55).

    Column subsets containing an excluded pair are skipped. Values are
    hashed with Python's deterministic int-tuple hash; the subsequent
    label-encoding pass collapses them to dense ids, so only injectivity
    matters.
    """
    excluded = [set(p) for p in excluded_pairs]
    cols = []
    for subset in itertools.combinations(range(X.shape[1]), degree):
        if any(e <= set(subset) for e in excluded):
            continue
        cols.append([hash(tuple(row)) for row in X[:, subset]])
    return np.array(cols).T


def _one_hot_split(
    X: np.ndarray, y: np.ndarray, test_size: float = 0.2
) -> Dataset:
    """Shared tail of every branch: 80/20 split (random_state=0), one-hot
    encoder fit on train+test jointly, sparse CSR output
    (arrange_real_data.py:59-64 etc.)."""
    from sklearn.model_selection import train_test_split
    from sklearn.preprocessing import OneHotEncoder

    X_train, X_test, y_train, y_test = train_test_split(
        X, y, test_size=test_size, random_state=0
    )
    encoder = OneHotEncoder(categories="auto")
    encoder.fit(np.vstack((X_train, X_test)))
    return Dataset(
        X_train=encoder.transform(X_train).tocsr(),
        y_train=np.asarray(y_train, dtype=np.float64),
        X_test=encoder.transform(X_test).tocsr(),
        y_test=np.asarray(y_test, dtype=np.float64),
    )


def prepare_amazon(input_dir: str) -> Dataset:
    """Kaggle amazon-employee-access; needs <input_dir>/train.csv."""
    import pandas as pd

    path = os.path.join(input_dir, "train.csv")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} missing — download train.csv from "
            "kaggle.com/c/amazon-employee-access-challenge"
        )
    df = pd.read_csv(path)
    X = df.loc[:, "RESOURCE":].values
    y = 2 * df["ACTION"].values - 1
    X = _label_encode_columns(X)
    X = np.hstack([X, hashed_interactions(X, degree=2)])
    X = _label_encode_columns(X)
    X = np.hstack([X, np.ones((X.shape[0], 1))])
    ds = _one_hot_split(X, y)
    ds.name = "amazon"
    return ds


def prepare_dna(input_dir: str, max_rows: int = 500_000) -> Dataset:
    """TU Berlin large-scale DNA; needs <input_dir>/features.csv."""
    path = os.path.join(input_dir, "features.csv")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} missing — fetch the dna dataset "
            "(ftp://largescale.ml.tu-berlin.de/largescale/dna/)"
        )
    with open(path) as fin:
        data = np.genfromtxt(itertools.islice(fin, 0, max_rows), delimiter=",")
    X, y = data[:, 1:], data[:, 0]
    n = X.shape[0]
    X = np.hstack([X, np.ones((n, 1)) / math.sqrt(n)])
    ds = _one_hot_split(X, y)
    ds.name = "dna"
    return ds


#: the genuine UCI covtype.data row layout fetch_covtype itself parses:
#: 10 quantitative columns, 4 wilderness-area indicators, 40 soil-type
#: indicators, then Cover_Type in 1..7 (55 comma-separated ints/row)
COVTYPE_N_FEATURES = 54


def prepare_covtype(input_dir: Optional[str] = None) -> Dataset:
    """UCI covertype (arrange_real_data.py:145-205 branch).

    Accepts either the raw UCI ``covtype.data``/``covtype.data.gz`` in
    ``input_dir`` (the 54-feature + Cover_Type layout — the same file
    sklearn's fetch_covtype downloads and parses), or an already-fetched
    sklearn cache (``input_dir`` as its data_home). The raw path makes the
    genuine schema drivable in a zero-egress sandbox."""
    raw = None
    for name in ("covtype.data", "covtype.data.gz"):
        p = os.path.join(input_dir or ".", name)
        if input_dir is not None and os.path.exists(p):
            raw = p
            break
    if raw is not None:
        import pandas as pd

        # pandas' C parser: the real UCI file is 581k rows (~75 MB) where
        # np.loadtxt's Python line loop would take minutes
        table = pd.read_csv(raw, header=None).to_numpy(dtype=np.float64)
        if table.ndim != 2 or table.shape[1] != COVTYPE_N_FEATURES + 1:
            raise ValueError(
                f"{raw}: expected {COVTYPE_N_FEATURES + 1} columns "
                f"(UCI covtype.data layout), got {table.shape}"
            )
        data, target = table[:, :COVTYPE_N_FEATURES], table[:, -1]
    else:
        try:
            from sklearn.datasets import fetch_covtype

            bunch = fetch_covtype(
                data_home=input_dir or None, download_if_missing=False
            )
        except OSError as e:
            raise FileNotFoundError(
                "covtype source missing — place the UCI covtype.data[.gz] "
                "in input_dir, or run sklearn.datasets.fetch_covtype() "
                "once with network access, or pass its data_home"
            ) from e
        data, target = bunch.data, bunch.target
    keep = target <= 2
    X = data[keep]
    y = np.where(target[keep] == 1, -1.0, 1.0)
    X = _label_encode_columns(X)
    X = np.hstack([X, np.ones((X.shape[0], 1))])
    ds = _one_hot_split(X, y)
    ds.name = "covtype"
    return ds


def prepare_kc_house(input_dir: str) -> Dataset:
    """KC house sales regression; needs <input_dir>/kc_house_data.csv."""
    import pandas as pd

    path = os.path.join(input_dir, "kc_house_data.csv")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} missing — download kc_house_data.csv "
            "(kaggle.com/harlfoxem/housesalesprediction)"
        )
    df = pd.read_csv(path)
    X = df.loc[:, "bedrooms":].values
    y = df["price"].values / 1e6  # arrange_real_data.py:225-226
    X = np.hstack([X, np.ones((X.shape[0], 1))])
    ds = _one_hot_split(X, y)
    ds.name = "kc_house_data"
    return ds


def prepare_breast_cancer(input_dir: Optional[str] = None) -> Dataset:
    """UCI Wisconsin breast-cancer — genuinely real (non-synthetic) data
    bundled inside scikit-learn, so it works in a zero-egress sandbox.

    Not one of the reference's four datasets (its CSVs/caches need network
    access); this routes REAL value distributions — 569 rows x 30
    continuous clinical features with heterogeneous scales and hundreds of
    distinct values per column — through the exact covtype pipeline
    (arrange_real_data.py:145-205 flow: per-column label encoding of
    continuous features, bias column, joint one-hot, CSR), proving the
    preparers on non-synthetic data (VERDICT r2 item 5).
    """
    from sklearn.datasets import load_breast_cancer

    bunch = load_breast_cancer()
    X = bunch.data
    y = 2.0 * bunch.target - 1.0  # {0,1} -> ±1 like covtype's class binarize
    X = _label_encode_columns(X)
    X = np.hstack([X, np.ones((X.shape[0], 1))])
    ds = _one_hot_split(X, y)
    ds.name = "breast_cancer"
    return ds


def prepare_diabetes(input_dir: Optional[str] = None) -> Dataset:
    """UCI diabetes regression — the genuinely real bundled counterpart of
    kc_house_data for the LINEAR model family (442 rows x 10 standardized
    clinical features; progression score target). Same pipeline shape as
    prepare_kc_house (arrange_real_data.py:207-253): bias column, 80/20
    split, one-hot of the label-encoded continuous columns, target scaled
    to O(1) like the reference's price/1e6."""
    from sklearn.datasets import load_diabetes

    bunch = load_diabetes()
    X = bunch.data
    y = bunch.target / 100.0  # O(1) target, ≙ price/1e6 scaling
    # like prepare_kc_house, raw values one-hot directly (the encoder's
    # categories='auto' handles continuous columns; no label-encode pass)
    X = np.hstack([X, np.ones((X.shape[0], 1))])
    ds = _one_hot_split(X, y)
    ds.name = "diabetes"
    return ds


PREPARERS: dict[str, Callable[..., Dataset]] = {
    "amazon": prepare_amazon,
    "amazon-dataset": prepare_amazon,  # the reference's directory name
    "dna": prepare_dna,
    "dna-dataset": prepare_dna,
    "dna-dataset/dna": prepare_dna,  # the reference's nested directory name
    "covtype": prepare_covtype,
    "kc_house_data": prepare_kc_house,
    # real (non-synthetic) data available without network access
    "breast_cancer": prepare_breast_cancer,
    "diabetes": prepare_diabetes,
}


def prepare(dataset: str, input_dir: str) -> Dataset:
    if dataset not in PREPARERS:
        raise ValueError(f"unknown dataset {dataset!r}; known: {sorted(PREPARERS)}")
    np.random.seed(0)  # reference determinism hook (arrange_real_data.py:27)
    return PREPARERS[dataset](input_dir)
