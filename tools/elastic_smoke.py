"""elastic-smoke: CPU end-to-end drive of the elastic membership
controller (`make elastic-smoke`).

Asserts, end to end:

  1. chaos-driven die-then-rejoin: ``ERASUREHEAD_CHAOS=
     3:worker_death:2,3:worker_revive:6`` kills live worker 3 at the 2nd
     chunk boundary and offers it back at the 6th — the controller must
     DETECT the death from telemetry alone (the -1 sentinel streak),
     re-layout W -> W-1, then accept the join and re-layout back to W;
  2. every decision and chunk row lands as a typed `membership` event and
     both the driver journal and the telemetry capture validate
     (obs/events.SCHEMA via the tools/validate_events.py logic);
  3. kill -> resume row rehydration: a run chaos-killed at an elastic
     chunk boundary (``kill:elastic:N``, preemption semantics) resumes
     from its checkpoint + aux ledger, REHYDRATES the completed chunks'
     rows bitwise from the journal, and finishes with the same rows and
     final-params digest as an uninterrupted baseline;
  4. `erasurehead-tpu report` renders the membership section from the
     journal.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from erasurehead_tpu.obs import events as obs_events  # noqa: E402
from erasurehead_tpu.utils.chaos import KILL_EXIT  # noqa: E402

W, R, CHUNK = 8, 40, 5
OUT = os.environ.get("ELASTIC_SMOKE_DIR", "/tmp/eh-elastic-smoke")

#: the child program both smoke legs run: a seeded elastic run with a
#: scripted 2-worker death, journaled + checkpointed into argv[1]
_CHILD = """
import json, os, sys
from erasurehead_tpu.data.synthetic import generate_gmm
from erasurehead_tpu import elastic
from erasurehead_tpu.utils.config import RunConfig

out = sys.argv[1]
W, R = 8, 40
ds = generate_gmm(32 * W, 16, n_partitions=W, seed=0)
cfg = RunConfig(scheme="naive", n_workers=W, n_stragglers=0, rounds=R,
                n_rows=32 * W, n_cols=16, lr_schedule=1.0,
                update_rule="AGD", add_delay=True, seed=0)
res = elastic.train_elastic_online(
    cfg, ds,
    elastic=elastic.ElasticConfig(chunk_rounds=5, death_rounds=3,
                                  timeout=4.0),
    deaths={6: 7, 7: 7},
    journal_dir=out,
    checkpoint_dir=os.path.join(out, "ckpt"),
    resume=os.environ.get("EH_ELASTIC_RESUME") == "1",
)
with open(os.path.join(out, "rows.json"), "w") as f:
    json.dump({
        "rows": [elastic.science_fields(r) for r in res.rows],
        "digest": res.rows[-1]["params_digest"],
        "resumed_from": res.resumed_from,
    }, f)
"""


def _run_child(out_dir, chaos=None, resume=False):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("ERASUREHEAD_CHAOS", None)
    env.pop("EH_ELASTIC_RESUME", None)
    if chaos:
        env["ERASUREHEAD_CHAOS"] = chaos
    if resume:
        env["EH_ELASTIC_RESUME"] = "1"
    return subprocess.run(
        [sys.executable, "-c", _CHILD, out_dir], env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )


def main() -> int:
    import numpy as np

    from erasurehead_tpu import elastic
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.utils import chaos as chaos_lib
    from erasurehead_tpu.utils.config import RunConfig

    shutil.rmtree(OUT, ignore_errors=True)
    os.makedirs(OUT, exist_ok=True)

    # ---- 1. chaos-driven die-then-rejoin ---------------------------------
    ds = generate_gmm(32 * W, 16, n_partitions=W, seed=0)
    cfg = RunConfig(
        scheme="naive", n_workers=W, n_stragglers=0, rounds=R,
        n_rows=32 * W, n_cols=16, lr_schedule=1.0, update_rule="AGD",
        add_delay=True, seed=0,
    )
    jdir = os.path.join(OUT, "chaos")
    os.makedirs(jdir, exist_ok=True)
    os.environ["ERASUREHEAD_CHAOS"] = (
        "3:worker_death:2,3:worker_revive:6"
    )
    chaos_lib.reset()
    try:
        events_path = os.path.join(jdir, "events.jsonl")
        with obs_events.capture(events_path):
            res = elastic.train_elastic_online(
                cfg, ds,
                elastic=elastic.ElasticConfig(
                    chunk_rounds=CHUNK, death_rounds=3, timeout=4.0
                ),
                journal_dir=jdir,
            )
    finally:
        del os.environ["ERASUREHEAD_CHAOS"]
    actions = [d["action"] for d in res.decisions]
    assert actions.count("relayout") == 2, res.decisions
    assert "death" in actions and "join" in actions, res.decisions
    widths = [e["n_workers"] for e in res.epochs]
    assert widths == [W, W - 1, W], widths
    hist = np.asarray(res.result.params_history)
    assert hist.shape[0] == R and np.isfinite(hist).all()
    print(
        f"elastic-smoke: chaos die-then-rejoin OK "
        f"(epoch widths {widths}, {len(res.rows)} chunk rows)"
    )

    # ---- 2. journal + capture validate -----------------------------------
    for path in (res.journal_path, events_path):
        errors = obs_events.validate_file(path)
        assert not errors, f"{path} invalid:\n" + "\n".join(errors)
    n_membership = sum(
        1
        for line in open(res.journal_path)
        if json.loads(line).get("type") == "membership"
    )
    assert n_membership >= len(res.rows) + 4  # rows + death/join/relayouts
    print(
        f"elastic-smoke: {n_membership} membership events validate "
        f"(journal + capture)"
    )

    # ---- 3. kill -> resume: rows rehydrate bitwise -----------------------
    base_dir = os.path.join(OUT, "base")
    kr_dir = os.path.join(OUT, "killresume")
    os.makedirs(base_dir, exist_ok=True)
    os.makedirs(kr_dir, exist_ok=True)
    p = _run_child(base_dir)
    assert p.returncode == 0, f"baseline leg rc={p.returncode}"
    p = _run_child(kr_dir, chaos="kill:elastic:4")
    assert p.returncode == KILL_EXIT, (
        f"kill leg rc={p.returncode}, want {KILL_EXIT}"
    )
    assert not os.path.exists(os.path.join(kr_dir, "rows.json"))
    p = _run_child(kr_dir, resume=True)
    assert p.returncode == 0, f"resume leg rc={p.returncode}"
    base = json.load(open(os.path.join(base_dir, "rows.json")))
    kr = json.load(open(os.path.join(kr_dir, "rows.json")))
    assert kr["resumed_from"] > 0, "resume leg did not actually resume"
    assert base["rows"] == kr["rows"], "kill->resume rows diverged"
    assert base["digest"] == kr["digest"], "final params digest diverged"
    errors = obs_events.validate_file(
        os.path.join(kr_dir, "elastic_journal.jsonl")
    )
    assert not errors, "kill->resume journal invalid:\n" + "\n".join(errors)
    print(
        f"elastic-smoke: kill->resume OK (resumed from round "
        f"{kr['resumed_from']}, {len(base['rows'])} rows bitwise, "
        f"digest {base['digest']})"
    )

    # ---- 4. report renders the membership section ------------------------
    from erasurehead_tpu.obs import report as report_lib

    rendered = report_lib.render([res.journal_path])
    assert "elastic membership:" in rendered
    assert "relayout" in rendered
    print("elastic-smoke: report renders the membership section")
    print(f"elastic-smoke: OK (artifacts -> {OUT})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
