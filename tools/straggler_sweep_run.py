"""Produce the north-star curve (time-to-target-loss vs n_stragglers for
AGC/EGC/FRC/avoidstragg/uncoded — BASELINE.json's stated metric) at a
chosen worker count, as a committed artifact pair
``artifacts/straggler_sweep_w{W}.{json,png}``.

The W=12 artifact came from an earlier ad-hoc run; this script is its
reproducible home, defaulting to the CANONICAL reference scale (W=30,
the flagship 13200x100 shape, 100 AGD rounds, the reference's seeded
delay schedule — run_approx_coding.sh:2-9's frame with W=30 folded onto
whatever devices exist). Simulated-clock science: platform-independent.

Usage: python tools/straggler_sweep_run.py [--workers 30] [--rounds 100]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=30)
    ap.add_argument("--rounds", type=int, default=100)
    ap.add_argument("--rows", type=int, default=13200)
    ap.add_argument("--cols", type=int, default=100)
    ap.add_argument("--num-collect", type=int, default=None,
                    help="AGC collection target (default W/2)")
    ap.add_argument("--events", action="store_true",
                    help="also write a run-telemetry event log "
                         "(artifacts/straggler_sweep_w{W}_events.jsonl; "
                         "render with `erasurehead-tpu report`)")
    ap.add_argument("--batch-trajectories", default=None,
                    choices=["on", "off", "auto"],
                    help="trajectory-batched dispatch (trainer."
                         "train_cohort): sweep entries sharing a device "
                         "data stack run as ONE compiled scan. Default: "
                         "ERASUREHEAD_BATCH_TRAJECTORIES env, else auto")
    ap.add_argument("--compute-mode", default="faithful",
                    choices=["faithful", "deduped"],
                    help="deduped stacks partition-major (scheme-"
                         "independent), letting --batch-trajectories "
                         "collapse the whole sweep into a few dispatches")
    ap.add_argument("--sweep-journal", default=None, metavar="DIR",
                    help="journal each trajectory's summary row into "
                         "DIR/sweep_journal.jsonl as it finishes — a "
                         "preempted sweep keeps everything already done. "
                         "Default: ERASUREHEAD_SWEEP_JOURNAL env, else "
                         "off")
    ap.add_argument("--resume-sweep", action="store_true",
                    help="skip trajectories the journal already completed "
                         "(rehydrated rows are identical to a fresh run's; "
                         "requires --sweep-journal or the env var). "
                         "ERASUREHEAD_RESUME_SWEEP=1 does the same")
    ns = ap.parse_args()
    W = ns.workers
    collect = ns.num_collect or W // 2

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.train import experiments, plots
    from erasurehead_tpu.train import journal as journal_lib
    from erasurehead_tpu.utils.config import (
        RunConfig,
        resolve_resume_sweep,
        resolve_sweep_journal,
    )

    journal_dir = resolve_sweep_journal(ns.sweep_journal)
    resume = resolve_resume_sweep(True if ns.resume_sweep else None)
    if resume and journal_dir is None:
        ap.error("--resume-sweep requires --sweep-journal DIR (or "
                 "ERASUREHEAD_SWEEP_JOURNAL)")
    journal = (
        journal_lib.SweepJournal(journal_dir, resume=resume)
        if journal_dir
        else None
    )

    rows = W * max(1, round(ns.rows / W))
    base = RunConfig(
        scheme="naive", n_workers=W, n_stragglers=0, num_collect=collect,
        rounds=ns.rounds, n_rows=rows, n_cols=ns.cols, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0,
        compute_mode=ns.compute_mode,
    )
    data = generate_gmm(rows, ns.cols, n_partitions=W, seed=0)

    # FRC-family schemes need (s+1) | W; MDS/avoidstragg take any s < W
    frc_s = [s for s in range(1, 6) if W % (s + 1) == 0]
    sweep = {
        "naive": [0],
        "cyccoded": list(range(1, 6)),
        "avoidstragg": list(range(1, 6)),
        "repcoded": frc_s,
        "approx": frc_s,
    }
    out_dir = os.path.join(os.path.dirname(__file__), "..", "artifacts")
    if ns.events:
        from erasurehead_tpu.obs import events as events_lib

        epath = os.path.join(out_dir, f"straggler_sweep_w{W}_events.jsonl")
        sink = events_lib.capture(epath)
    else:
        epath, sink = None, None
    t0 = time.time()
    try:
        if sink is not None:
            with sink:
                summaries = experiments.straggler_sweep(
                    base, data, sweep, batch=ns.batch_trajectories,
                    journal=journal,
                )
            print(f"events -> {epath}", file=sys.stderr)
        else:
            summaries = experiments.straggler_sweep(
                base, data, sweep, batch=ns.batch_trajectories,
                journal=journal,
            )
    finally:
        if journal is not None:
            journal.close()
    if journal is not None:
        print(f"sweep journal -> {journal.path}", file=sys.stderr)
    print(f"sweep: {len(summaries)} runs in {time.time() - t0:.0f}s",
          file=sys.stderr)
    jpath = os.path.join(out_dir, f"straggler_sweep_w{W}.json")
    with open(jpath, "w") as f:
        json.dump([s.row() for s in summaries], f, indent=1)
    by_scheme: dict[str, list] = {}
    for s in summaries:
        by_scheme.setdefault(s.config.scheme.value, []).append(s)
    ppath = plots.save_sweep_figure(
        by_scheme,
        os.path.join(out_dir, f"straggler_sweep_w{W}.png"),
        title=f"time to target loss vs stragglers (W={W}, AGD)",
    )
    for s in summaries:
        print(f"  {s.label}: time_to_target="
              f"{s.time_to_target if s.time_to_target is not None else 'never'}"
              f" sim_rate={s.sim_steps_per_sec:.3f} it/s",
          file=sys.stderr)
    print(json.dumps({"json": jpath, "png": ppath, "runs": len(summaries)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
