"""Micro-profile the PaddedRows hot ops at the covtype canonical shape on
TPU, inside one dispatch (the relay's ~60ms round trip would otherwise
swamp every number). Compares rmatvec lowerings to pick the fastest:

  scatter      — current .at[idx].add (unsorted scatter-add)
  sort-in-jit  — argsort the flat column ids per call (X is loop-invariant
                 in the training scan, so XLA may hoist the sort)
  presorted    — segment_sum with host-presorted ids (indices_are_sorted)

Usage: python tools/profile_sparse.py [--slots 90] [--rows 13203]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from _relay import with_retries


def time_scanned(fn, args, iters=30, reps=3):
    """Seconds/iteration inside one jitted scan; fn(carry, *args)->carry."""

    @jax.jit
    def many(c0):
        def body(c, _):
            return fn(c, *args), None

        cN, _ = jax.lax.scan(body, c0, None, length=iters)
        return cN

    c0 = jnp.zeros(F, jnp.float32)  # carry is always the beta vector
    with_retries(lambda: jax.block_until_ready(many(c0)))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(many(c0))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts)) / iters


ap = argparse.ArgumentParser()
ap.add_argument("--slots", type=int, default=90)
ap.add_argument("--rows", type=int, default=13203)
ap.add_argument("--nnz", type=int, default=12)
ap.add_argument("--cols", type=int, default=15509)
ap.add_argument(
    "--only", default="",
    help="comma-separated substrings: measure only matching candidates "
         "(each costs a slow relay compile, so the sweep runs this profile "
         "as small tagged groups that fit a per-entry timeout)",
)
args = ap.parse_args()


def want(name: str) -> bool:
    return (not args.only) or any(
        s and s in name for s in args.only.split(",")
    )

M, R, K, F = args.slots, args.rows, args.nnz, args.cols
print(f"profile: {jax.devices()[0].platform} M={M} R={R} K={K} F={F}",
      file=sys.stderr)

rng = np.random.default_rng(0)
idx = rng.integers(0, F, (M, R, K)).astype(np.int32)
val = np.ones((M, R, K), np.float32)
y = np.sign(rng.standard_normal((M, R))).astype(np.float32)
idx_j, val_j, y_j = jnp.asarray(idx), jnp.asarray(val), jnp.asarray(y)

# host-presorted flat ids per slot
flat = idx.reshape(M, R * K)
order = np.argsort(flat, axis=1, kind="stable").astype(np.int32)
sorted_ids = np.take_along_axis(flat, order, axis=1)
order_j, sorted_ids_j = jnp.asarray(order), jnp.asarray(sorted_ids)

results = {}


def dep(beta, g):
    """Feed g back into beta so nothing is elided."""
    return g / (jnp.linalg.norm(g) + 1.0)


# --- margin gather only ----------------------------------------------------
def margin(beta, idxs, vals, ys):
    g = jax.vmap(
        lambda i, v: jnp.sum(v * jnp.take(beta, i, axis=0), axis=1)
    )(idxs, vals)
    # reduce back to F so the carry shape survives: cheap bincount-free proxy
    return beta * 0.999 + jnp.sum(g) / F


if want("margin_gather"):
    results["margin_gather_ms"] = round(
        time_scanned(margin, (idx_j, val_j, y_j)) * 1e3, 3
    )
    print(f"profile: margin {results['margin_gather_ms']}ms", file=sys.stderr)


# --- rmatvec: current unsorted scatter ------------------------------------
def scatter(beta, idxs, vals, ys):
    def one(i, v, s):
        contrib = (v * s[:, None]).reshape(-1)
        return jnp.zeros(F, jnp.float32).at[i.reshape(-1)].add(contrib)

    g = jax.vmap(one)(idxs, vals, ys).sum(0)
    return dep(beta, g)


if want("scatter_ms"):
    results["scatter_ms"] = round(
        time_scanned(scatter, (idx_j, val_j, y_j)) * 1e3, 3
    )
    print(f"profile: scatter {results['scatter_ms']}ms", file=sys.stderr)


# --- rmatvec: sort inside jit (hoistable: ids are loop-invariant) ---------
def sortjit(beta, idxs, vals, ys):
    def one(i, v, s):
        flat_i = i.reshape(-1)
        o = jnp.argsort(flat_i)
        contrib = (v * s[:, None]).reshape(-1)[o]
        return jax.ops.segment_sum(
            contrib, flat_i[o], num_segments=F, indices_are_sorted=True
        )

    g = jax.vmap(one)(idxs, vals, ys).sum(0)
    return dep(beta, g)


if want("sort_in_jit"):
    results["sort_in_jit_ms"] = round(
        time_scanned(sortjit, (idx_j, val_j, y_j)) * 1e3, 3
    )
    print(f"profile: sort_in_jit {results['sort_in_jit_ms']}ms",
          file=sys.stderr)


# --- rmatvec: host-presorted segment_sum ----------------------------------
def presorted(beta, idxs, vals, ys, orders, sids):
    def one(i, v, s, o, sid):
        contrib = (v * s[:, None]).reshape(-1)[o]
        return jax.ops.segment_sum(
            contrib, sid, num_segments=F, indices_are_sorted=True
        )

    g = jax.vmap(one)(idxs, vals, ys, orders, sids).sum(0)
    return dep(beta, g)


if want("presorted"):
    results["presorted_ms"] = round(
        time_scanned(
            presorted, (idx_j, val_j, y_j, order_j, sorted_ids_j)
        ) * 1e3,
        3,
    )
    print(f"profile: presorted {results['presorted_ms']}ms", file=sys.stderr)

results["platform"] = jax.devices()[0].platform
results["shape"] = [M, R, K, F]


# --- margin via row-gather from a lane-replicated [F, L] table ------------
# lax.map (not vmap) over slots: vmapping fuses all M slots' [R*K, L]
# gathers into one materialized [M, R, K, L] temp — 9 GB at L=128, an
# instant OOM. A memory-bounded production lowering walks slots
# sequentially, keeping the live temp at [R*K, L] (~77 MB).
def margin_rowgather_fn(L):
    def f(beta, idxs, vals, ys):
        table = jnp.broadcast_to(beta[:, None], (F, L))

        def one(iv):
            i, v = iv
            g = jnp.take(table, i.reshape(-1), axis=0)  # [R*K, L]
            return (v.reshape(-1, 1) * g).reshape(i.shape[0], -1, L).sum(1)

        p = jax.lax.map(one, (idxs, vals))  # [M, R, L]
        return beta * 0.999 + jnp.sum(p[..., 0]) / F
    return f


for L in (8, 128):
    if not want(f"margin_rowgather{L}"):
        continue
    results[f"margin_rowgather{L}_ms"] = round(
        time_scanned(margin_rowgather_fn(L), (idx_j, val_j, y_j)) * 1e3, 3
    )
    print(f"profile: margin_rowgather{L} "
          f"{results[f'margin_rowgather{L}_ms']}ms", file=sys.stderr)


# --- rmatvec via row-scatter into [F, L] (lax.map: same OOM story) --------
def scatter_rows_fn(L):
    def f(beta, idxs, vals, ys):
        def one(ivs):
            i, v, s = ivs
            contrib = (v * s[:, None]).reshape(-1, 1)
            rows = jnp.broadcast_to(contrib, (contrib.shape[0], L))
            out = jnp.zeros((F, L), jnp.float32).at[i.reshape(-1)].add(rows)
            return out[:, 0]
        g = jax.lax.map(one, (idxs, vals, ys)).sum(0)
        return dep(beta, g)
    return f


for L in (8, 128):
    if not want(f"scatter_rows{L}"):
        continue
    results[f"scatter_rows{L}_ms"] = round(
        time_scanned(scatter_rows_fn(L), (idx_j, val_j, y_j)) * 1e3, 3
    )
    print(f"profile: scatter_rows{L} "
          f"{results[f'scatter_rows{L}_ms']}ms", file=sys.stderr)


# --- packed-row variants: table reshaped [F/P, P], gather row idx//P and
# select lane idx%P via a fused one-hot — vectorized addressing without the
# lane-replication's P x table blowup; scatter accumulates masked P-wide
# rows into a beta-sized [F/P, P] accumulator -------------------------------
def margin_packed_fn(P):
    Fp = -(-F // P) * P

    def f(beta, idxs, vals, ys):
        table = jnp.pad(beta, (0, Fp - F)).reshape(Fp // P, P)

        def one(iv):
            i, v = iv
            flat = i.reshape(-1)
            rows = jnp.take(table, flat // P, axis=0)  # [RK, P]
            sel = jax.nn.one_hot(flat % P, P, dtype=jnp.float32)
            g = jnp.sum(rows * sel, axis=1).reshape(i.shape)
            return jnp.sum(v * g, axis=1)

        p = jax.lax.map(one, (idxs, vals))
        return beta * 0.999 + jnp.sum(p) / F

    return f


def scatter_packed_fn(P):
    Fp = -(-F // P) * P

    def f(beta, idxs, vals, ys):
        def one(ivs):
            i, v, s = ivs
            flat = i.reshape(-1)
            contrib = (v * s[:, None]).reshape(-1, 1)
            rows = contrib * jax.nn.one_hot(flat % P, P, dtype=jnp.float32)
            out = (
                jnp.zeros((Fp // P, P), jnp.float32)
                .at[flat // P]
                .add(rows)
            )
            return out.reshape(Fp)[:F]

        g = jax.lax.map(one, (idxs, vals, ys)).sum(0)
        return dep(beta, g)

    return f


for P in (8, 128):
    if want(f"margin_packed{P}"):
        results[f"margin_packed{P}_ms"] = round(
            time_scanned(margin_packed_fn(P), (idx_j, val_j, y_j)) * 1e3, 3
        )
        print(f"profile: margin_packed{P} "
              f"{results[f'margin_packed{P}_ms']}ms", file=sys.stderr)
    if want(f"scatter_packed{P}"):
        results[f"scatter_packed{P}_ms"] = round(
            time_scanned(scatter_packed_fn(P), (idx_j, val_j, y_j)) * 1e3, 3
        )
        print(f"profile: scatter_packed{P} "
              f"{results[f'scatter_packed{P}_ms']}ms", file=sys.stderr)


# --- pair-table variants (one-hot field structure): fold field pairs into
# a per-iteration [B, B] sum table so the margin needs K/2 gathers per row
# instead of K, and the gradient scatters into [B^2] pair accumulators
# then marginalizes (row/col sums) — halving the serialized lookup count,
# the measured bound. Valid for val=1 one-hot data with per-field blocks
# (the canonical covtype/amazon structure, generate_onehot); B = F // K
# (any remainder columns are out of the experiment's index range, which
# is immaterial for timing). -----------------------------------------------
B = F // K
if K % 2 == 0 and B >= 2:
    # field-structured local categories and fused per-pair indices, built
    # on host like PaddedRows construction would (data, loop-invariant)
    loc = rng.integers(0, B, (M, R, K))
    pair_idx_j = jnp.asarray(
        (loc[:, :, 0::2] * B + loc[:, :, 1::2]).astype(np.int32)
    )  # [M, R, K/2], each entry indexes its pair's [B*B] table

    def margin_pairs(beta, pidx, ys):
        blocks = beta[: K * B].reshape(K, B)
        p = jnp.zeros((M, R), jnp.float32)
        for pr in range(K // 2):
            # the pair's [B*B] sum table rebuilds every iteration (beta
            # changes); the build is a vectorized outer sum, tiny vs the
            # gathers it replaces
            table = (
                blocks[2 * pr][:, None] + blocks[2 * pr + 1][None, :]
            ).reshape(B * B)
            p = p + jnp.take(table, pidx[:, :, pr], axis=0)
        # same reduction as every other margin variant (apples-to-apples)
        return beta * 0.999 + jnp.sum(p) / F

    if want("margin_pairs"):
        results["margin_pairs_ms"] = round(
            time_scanned(margin_pairs, (pair_idx_j, y_j)) * 1e3, 3
        )
        print(f"profile: margin_pairs {results['margin_pairs_ms']}ms",
              file=sys.stderr)

    def scatter_pairs(beta, pidx, ys):
        def one(ps):
            pi, s = ps
            gs = []
            for pr in range(K // 2):
                acc = jnp.zeros(B * B, jnp.float32).at[pi[:, pr]].add(s)
                t = acc.reshape(B, B)
                gs.append(t.sum(axis=1))  # field 2*pr marginal
                gs.append(t.sum(axis=0))  # field 2*pr + 1 marginal
            return jnp.concatenate(gs)

        g = jax.lax.map(one, (pidx, ys)).sum(0)
        return dep(beta, jnp.pad(g, (0, F - K * B)))

    if want("scatter_pairs"):
        results["scatter_pairs_ms"] = round(
            time_scanned(scatter_pairs, (pair_idx_j, y_j)) * 1e3, 3
        )
        print(f"profile: scatter_pairs {results['scatter_pairs_ms']}ms",
              file=sys.stderr)

    # --- the PRODUCTION flat lowering's exact shapes (features.
    # flatten_rows + step.make_flat_grad_fn): one [M*R, K/2] code array,
    # and ONE [B*B] accumulator per pair over ALL rows — no per-slot
    # batch, no lax.map. The fields regression taught that candidates
    # must match the production lowering to predict it. Names dodge the
    # margin_pairs/scatter_pairs substrings so the main sweep's --only
    # groups never pick these up. ----------------------------------------
    def flatpairs_margin(beta, pidx, ys):
        blocks = beta[: K * B].reshape(K, B)
        pf = pidx.reshape(M * R, K // 2)
        p = jnp.zeros(M * R, jnp.float32)
        for pr in range(K // 2):
            table = (
                blocks[2 * pr][:, None] + blocks[2 * pr + 1][None, :]
            ).reshape(B * B)
            p = p + jnp.take(table, pf[:, pr], axis=0)
        return beta * 0.999 + jnp.sum(p) / F

    if want("flatpairs_margin"):
        results["flatpairs_margin_ms"] = round(
            time_scanned(flatpairs_margin, (pair_idx_j, y_j)) * 1e3, 3
        )
        print(
            f"profile: flatpairs_margin "
            f"{results['flatpairs_margin_ms']}ms", file=sys.stderr,
        )

    def flatpairs_scatter(beta, pidx, ys):
        pf = pidx.reshape(M * R, K // 2)
        s = ys.reshape(M * R)
        gs = []
        for pr in range(K // 2):
            acc = jnp.zeros(B * B, jnp.float32).at[pf[:, pr]].add(s)
            t = acc.reshape(B, B)
            gs.append(t.sum(axis=1))
            gs.append(t.sum(axis=0))
        g = jnp.concatenate(gs)
        return dep(beta, jnp.pad(g, (0, F - K * B)))

    if want("flatpairs_scatter"):
        results["flatpairs_scatter_ms"] = round(
            time_scanned(flatpairs_scatter, (pair_idx_j, y_j)) * 1e3, 3
        )
        print(
            f"profile: flatpairs_scatter "
            f"{results['flatpairs_scatter_ms']}ms", file=sys.stderr,
        )

    # --- composed flat x lanes margin (the production fields+lanes
    # lowering, ops/features._lanes_fields_matvec): lane-replicated pair
    # tables behind a barrier, flat [M*R] rows. Predicts the
    # *_fields_lanes8_flat bench entries. ---------------------------------
    def flatlanes_margin_fn(L):
        def f(beta, pidx, ys):
            blocks = beta[: K * B].reshape(K, B)
            pf = pidx.reshape(M * R, K // 2)
            acc = jnp.zeros((M * R, L), jnp.float32)
            for pr in range(K // 2):
                table = (
                    blocks[2 * pr][:, None] + blocks[2 * pr + 1][None, :]
                ).reshape(B * B)
                wide = jax.lax.optimization_barrier(
                    jnp.broadcast_to(table[:, None], (B * B, L))
                )
                acc = acc + jnp.take(wide, pf[:, pr], axis=0)
            p = acc.sum(axis=1) * (1.0 / L)
            return beta * 0.999 + jnp.sum(p) / F
        return f

    for L in (8,):
        if want(f"flatlanes_margin{L}"):
            results[f"flatlanes_margin{L}_ms"] = round(
                time_scanned(
                    flatlanes_margin_fn(L), (pair_idx_j, y_j)
                ) * 1e3, 3,
            )
            print(
                f"profile: flatlanes_margin{L} "
                f"{results[f'flatlanes_margin{L}_ms']}ms", file=sys.stderr,
            )

    # --- scatter as one-hot MATMUL (segment-sum on the MXU): the scalar
    # scatter-add serializes ~7ns per read-modify-write; instead, per
    # field, g_k[b] = sum_n [local_n == b] * s_n is a [C]x[C,B] matmul
    # over row chunks — the compare+select builds an exact 0/1 one-hot
    # (any dtype), the MXU does the reduction, and the chunk scan keeps
    # the live one-hot at [C, B]. Two dtype variants: f32/HIGHEST (exact
    # accumulation) and bf16 operands (s rounded to bf16 — the speed
    # ceiling; one-hot entries are exact either way). ---------------------
    loc_j = jnp.asarray(loc.astype(np.int32))

    def scatter_onehot_fn(C, dtype, precision):
        MR = M * R
        Np = -(-MR // C) * C

        def f(beta, locs, ys):
            lf = jnp.pad(
                locs.reshape(MR, K), ((0, Np - MR), (0, 0))
            ).reshape(Np // C, C, K)
            # padded rows carry s=0: they hit code 0 with zero weight
            sc = jnp.pad(ys.reshape(MR), (0, Np - MR)).reshape(Np // C, C)
            iota = jnp.arange(B, dtype=jnp.int32)

            def chunk(g, xs):
                l, sv = xs  # [C, K], [C]
                svd = sv.astype(dtype)
                outs = []
                for k in range(K):
                    oh = (l[:, k][:, None] == iota[None, :]).astype(dtype)
                    outs.append(
                        jnp.matmul(
                            svd, oh,
                            precision=precision,
                            preferred_element_type=jnp.float32,
                        )
                    )
                return g + jnp.stack(outs), None

            g0 = jnp.zeros((K, B), jnp.float32)
            g, _ = jax.lax.scan(chunk, g0, (lf, sc))
            return dep(beta, jnp.pad(g.reshape(-1), (0, F - K * B)))

        return f

    for nm, dt, prec in (
        ("scatter_onehot_f32", jnp.float32, jax.lax.Precision.HIGHEST),
        ("scatter_onehot_bf16", jnp.bfloat16, None),
    ):
        if want(nm):
            results[f"{nm}_ms"] = round(
                time_scanned(
                    scatter_onehot_fn(4096, dt, prec), (loc_j, y_j)
                ) * 1e3, 3,
            )
            print(
                f"profile: {nm} {results[f'{nm}_ms']}ms", file=sys.stderr
            )

    # --- margin as one-hot MATMUL: the mirror trick — per field,
    # p_n += sum_b [local_n == b] * beta_k[b] is onehot [C, B] @ beta_k,
    # the same compare cost as the one-hot scatter with the MXU replacing
    # every gather. If both directions go MXU the sparse step does no
    # serialized lookups at all. ------------------------------------------
    def margin_onehot_fn(C, dtype, prec):
        MR = M * R
        Np = -(-MR // C) * C

        def f(beta, locs, ys):
            blocks = beta[: K * B].reshape(K, B)
            lf = jnp.pad(
                locs.reshape(MR, K), ((0, Np - MR), (0, 0))
            ).reshape(Np // C, C, K)

            def chunk(l):
                p = jnp.zeros(C, jnp.float32)
                for k in range(K):
                    iota = jnp.arange(B, dtype=jnp.int32)
                    oh = (l[:, k][:, None] == iota[None, :]).astype(dtype)
                    p = p + jnp.matmul(
                        oh, blocks[k].astype(dtype),
                        precision=prec,
                        preferred_element_type=jnp.float32,
                    )
                return p

            p = jax.lax.map(chunk, lf)  # [Np//C, C]
            return beta * 0.999 + jnp.sum(p) / F

        return f

    for nm, dt, prec in (
        ("margin_onehot_f32", jnp.float32, jax.lax.Precision.HIGHEST),
        ("margin_onehot_bf16", jnp.bfloat16, None),
    ):
        if want(nm):
            results[f"{nm}_ms"] = round(
                time_scanned(
                    margin_onehot_fn(4096, dt, prec), (loc_j, y_j)
                ) * 1e3, 3,
            )
            print(
                f"profile: {nm} {results[f'{nm}_ms']}ms", file=sys.stderr
            )

print(json.dumps(results))
