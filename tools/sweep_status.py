#!/usr/bin/env python
"""Print the number of measurement-sweep tags not yet captured in
tools/measurements.jsonl (0 means the full program is complete). Tag
lists are parsed from every tpu_measurements*.sh program so the scripts
and this count never drift."""
import json
import pathlib
import re
import sys

root = pathlib.Path(__file__).resolve().parent
tags = []
for script in sorted(root.glob("tpu_measurements*.sh")):
    for line in script.read_text().splitlines():
        m = re.match(r'\s*run\s+"?([A-Za-z0-9_${}]+)"?\s+\d+', line)
        if m:
            tags.append(m.group(1))
expanded = []
for t in tags:
    if "${shape}" in t:
        for shape in ("covtype", "amazon"):
            expanded.append(t.replace("${shape}", shape))
    else:
        expanded.append(t)
captured = set()
out = root / "measurements.jsonl"
if out.exists():
    for line in out.read_text().splitlines():
        try:
            captured.add(json.loads(line)["tag"])
        except (json.JSONDecodeError, KeyError):
            pass
missing = [t for t in expanded if t not in captured]
if "-v" in sys.argv[1:]:
    for t in missing:
        print("missing:", t, file=sys.stderr)
print(len(missing))
