#!/usr/bin/env python
"""Schema-check a run-telemetry events.jsonl (obs/events.py).

Thin CLI over erasurehead_tpu.obs.events.validate_file — the validation
logic lives in the package so the tests, `make telemetry-smoke`, and this
tool can never drift. Checks: every line parses, record types are known,
required keys are present, seq is monotonic per logger, chunked
rounds/decode records have strictly increasing round indices per
(run, trajectory, layer) stream — the optional `layer` tag (a
non-negative int) marks a per-layer decode-error-vs-depth series under
blockwise gradient coding (obs/events.emit_layer_decode_chunks) —,
sweep_trajectory journal records (train/journal.py) carry a known status
("ok"/"diverged"), a non-empty key and an object row, serve-daemon
records (erasurehead_tpu/serve/) are internally consistent (`request`
names its tenant/request_id/label, `pack`'s trajectory count matches its
label list, `admit` carries non-negative byte figures, `evict` names its
reason), adaptive-controller `adapt` records (erasurehead_tpu/adapt/)
carry a non-negative chunk-start round, a non-empty arm label and a
known reason (warmup/exploit/explore/regime_shift — obs/events.
ADAPT_REASONS), elastic `membership` records (erasurehead_tpu/elastic/)
carry a non-negative round, a known action (death/join/relayout/probe/
chunk — obs/events.MEMBERSHIP_ACTIONS), a positive worker count and
well-formed worker-id lists, what-if engine `whatif` records
(erasurehead_tpu/whatif/) carry a non-empty spec_hash and a known kind
(grid/point/surface/rehydrate — obs/events.WHATIF_KINDS) with per-kind
field checks (point records name their grid point and feasibility
verdict, grid records carry non-negative point counts), telemetry-plane
records are internally consistent (`critical_path` ledgers reconcile to
their measured totals within obs/events.CRITICAL_PATH_TOL with
fractions in [0, 1], `regime` snapshots carry a known kind
(exp/heavytail/unknown — obs/events.REGIME_KINDS) and non-negative
rate/counts, `slo` burn-rate records name their tenant with
breaches <= window_requests), and every run_start has a matching
run_end. Sweep journals and serve event logs are events.jsonl files
too — point this tool at DIR/sweep_journal.jsonl or the daemon's
--events log to check them.

Usage: python tools/validate_events.py events.jsonl [more.jsonl ...]
Exit 0 = all files valid; 1 = errors (printed, one per line).
"""

import os
import sys

# runnable from anywhere without an install (the tools/ convention)
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if not argv:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    from erasurehead_tpu.obs import events as events_lib

    n_errors = 0
    for path in argv:
        try:
            errors = events_lib.validate_file(path)
        except OSError as e:
            errors = [str(e)]
        for err in errors:
            print(f"{path}: {err}")
        n_errors += len(errors)
        if not errors:
            print(f"{path}: OK")
    return 1 if n_errors else 0


if __name__ == "__main__":
    sys.exit(main())
