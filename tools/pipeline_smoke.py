"""pipeline-smoke: CPU sync vs tau=1 pipelined race under exp(2.0).

`make pipeline-smoke` asserts, end to end:

  1. the pipelined run's simulated time-to-target is <= the synchronous
     run's on the identical straggler world (the overlap win the mode
     exists for), and both reach the target;
  2. pipelined training replays deterministically: a rerun of the same
     config is bitwise-identical in params history AND timeset (stale,
     not async-racy — the bounded-staleness contract);
  3. tau=0 collapses exactly: pipeline_depth=0 is bitwise today's
     synchronous trainer (params history, timeset, decode error);
  4. the typed pipeline telemetry lands and validates: the run emits a
     "dispatch_ahead" event, the post-run staleness-vs-coding split
     emits "stale_decode", and the whole event log passes
     obs/events.validate_lines;
  5. the refusal matrix holds where the smoke can cheaply check it:
     exact-decode schemes and momentum rules refuse with typed reasons.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from erasurehead_tpu.data.synthetic import generate_gmm  # noqa: E402
from erasurehead_tpu.obs import decode as decode_lib  # noqa: E402
from erasurehead_tpu.obs import events as obs_events  # noqa: E402
from erasurehead_tpu.train import evaluate, experiments, trainer  # noqa: E402
from erasurehead_tpu.utils.config import (  # noqa: E402
    PipelineRefusal,
    RunConfig,
)

W, S, R = 8, 1, 80
ROWS, COLS = 256, 16
TARGET = 0.15
OUT = "/tmp/eh-pipeline-smoke"

#: lr_schedule is EXPLICIT: the default schedule sits at GD's stability
#: edge and tau=1 staleness shrinks the stable region
COMMON = dict(
    scheme="avoidstragg", n_workers=W, n_stragglers=S, rounds=R,
    n_rows=ROWS, n_cols=COLS, update_rule="GD", compute_mode="deduped",
    add_delay=True, delay_mean=2.0, lr_schedule=1.0, seed=3,
)


def _bitwise(a, b, what: str) -> None:
    import jax

    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb), f"{what}: leaf count differs"
    for x, y in zip(la, lb):
        assert np.array_equal(np.asarray(x), np.asarray(y)), (
            f"{what}: arrays differ"
        )


def _time_to_target(ds, result):
    model = trainer.build_model(result.config)
    n = result.n_train
    ev = evaluate.replay(
        model, result.config.model, result.params_history,
        ds.X_train[:n], ds.y_train[:n], ds.X_test, ds.y_test,
    )
    loss = np.asarray(ev.training_loss, dtype=np.float64)
    return experiments.time_to_target_loss(loss, result.timeset, TARGET)


def main() -> int:
    os.makedirs(OUT, exist_ok=True)
    ds = generate_gmm(ROWS, COLS, n_partitions=W, seed=0)

    # 1) the race: sync vs tau=1 pipelined, same arrival world
    sync = trainer.train(RunConfig(**COMMON), ds, measure=False)
    events_path = os.path.join(OUT, "events.jsonl")
    with obs_events.capture(events_path):
        pipe = trainer.train(
            RunConfig(**COMMON, pipeline_depth=1), ds, measure=False
        )
        split = decode_lib.emit_staleness_split("pipeline-smoke", pipe, ds)
    t_sync, t_pipe = _time_to_target(ds, sync), _time_to_target(ds, pipe)
    assert t_sync is not None, "synchronous run never reached the target"
    assert t_pipe is not None, "pipelined run never reached the target"
    assert t_pipe <= t_sync, (
        f"pipelined time-to-target {t_pipe:.3f}s worse than "
        f"synchronous {t_sync:.3f}s"
    )
    print(
        f"pipeline-smoke: time-to-target(loss<={TARGET}) sync "
        f"{t_sync:.3f}s vs pipelined {t_pipe:.3f}s "
        f"({t_sync / t_pipe:.2f}x), staleness_share "
        f"{split['staleness_share']:.3f}"
    )

    # 2) deterministic replay: rerun is bitwise
    pipe2 = trainer.train(
        RunConfig(**COMMON, pipeline_depth=1), ds, measure=False
    )
    _bitwise(pipe.params_history, pipe2.params_history, "pipelined rerun")
    assert np.array_equal(pipe.timeset, pipe2.timeset)
    print("pipeline-smoke: pipelined replay bitwise OK")

    # 3) tau=0 is bitwise the synchronous trainer
    tau0 = trainer.train(
        RunConfig(**COMMON, pipeline_depth=0), ds, measure=False
    )
    _bitwise(sync.params_history, tau0.params_history, "tau=0 collapse")
    assert np.array_equal(sync.timeset, tau0.timeset)
    assert np.array_equal(sync.decode_error, tau0.decode_error)
    print("pipeline-smoke: tau=0 bitwise-synchronous OK")

    # 4) typed telemetry validates
    with open(events_path) as f:
        lines = f.readlines()
    errors = obs_events.validate_lines(lines)
    assert not errors, "event log invalid:\n" + "\n".join(errors)
    types = [json.loads(ln).get("type") for ln in lines]
    assert "dispatch_ahead" in types, f"no dispatch_ahead event: {types}"
    assert "stale_decode" in types, f"no stale_decode event: {types}"
    print(f"pipeline-smoke: {len(lines)} events validate "
          f"(dispatch_ahead + stale_decode present)")

    # 5) refusal matrix spot-checks
    for kwargs, want in (
        ({**COMMON, "scheme": "cyccoded"}, "exact_decode"),
        ({**COMMON, "update_rule": "AGD"}, "momentum_unproven"),
    ):
        try:
            RunConfig(**kwargs, pipeline_depth=1)
            raise AssertionError(f"{want}: config did not refuse")
        except PipelineRefusal as e:
            assert e.reason == want, (e.reason, want)
    print("pipeline-smoke: refusal matrix spot-checks OK")
    print(f"pipeline-smoke: OK (events -> {events_path})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
