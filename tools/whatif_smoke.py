"""whatif-smoke: CPU end-to-end drive of the what-if engine.

`make whatif-smoke` asserts, end to end:

  1. a tiny grid spec runs through the engine (feasibility filter ->
     on-device Monte-Carlo arrival sampling -> cohort dispatches ->
     surface reduction) and saves its artifact (surface_rows.jsonl +
     surface.npz), with infeasible points recorded-not-dispatched;
  2. every engine phase lands as a typed `whatif` event and the whole
     event log validates (obs/events.SCHEMA);
  3. the adapt priors round-trip: the reloaded surface seeds an
     AdaptiveController whose first decision EXPLOITS the simulated
     ranking instead of burning warm-up chunks (cold-start fix);
  4. the serve ETA round-trip: an in-process daemon holding the surface
     quotes a positive expected time-to-target on an accepted request;
  5. rerunning the IDENTICAL spec is bitwise idempotent, twice over:
     with the artifact present the engine REHYDRATES (no simulation),
     and a forced re-simulation into a fresh directory reproduces both
     artifact files byte for byte.
"""

import json
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from erasurehead_tpu import adapt  # noqa: E402
from erasurehead_tpu.obs import events as obs_events  # noqa: E402
from erasurehead_tpu.whatif import (  # noqa: E402
    GridSpec,
    PolicySpec,
    RegimeSpec,
    Surface,
    run_whatif,
)

OUT = "/tmp/eh-whatif-smoke"


def _spec() -> GridSpec:
    return GridSpec(
        policies=(
            PolicySpec("naive"),
            PolicySpec("avoidstragg"),
            PolicySpec("approx", num_collect=4),
            # infeasible on purpose at s=3: FRC needs (s+1) | W and
            # 6 % 4 != 0 — the filter must record it, never dispatch it
            PolicySpec("repcoded"),
            # infeasible everywhere: the deadline scheme without a
            # deadline (needs_deadline) — same contract, other branch
            PolicySpec("deadline"),
        ),
        n_workers=(6,),
        n_stragglers=(1, 3),
        regimes=(RegimeSpec(mean=0.5),),
        n_seeds=4,
        rounds=12,
        n_rows=96,
        n_cols=8,
    )


def _file_bytes(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def main() -> int:
    shutil.rmtree(OUT, ignore_errors=True)
    run_dir = os.path.join(OUT, "surface")
    spec = _spec()

    # 1) grid -> surface artifact, with the engine's event stream captured
    events_path = os.path.join(OUT, "events.jsonl")
    with obs_events.capture(events_path):
        surf = run_whatif(spec, out_dir=run_dir)
    print(surf.format_table())
    infeasible = [r for r in surf.rows if not r["feasible"]]
    assert infeasible, "the seeded FRC-divisibility point must be recorded"
    assert all(r["reason"] for r in infeasible)
    assert all(r["expected_time_to_target"] is None for r in infeasible)
    feasible = surf.feasible_rows()
    assert feasible and all(
        r["expected_time_to_target"] is not None for r in feasible
    )
    print(
        f"whatif-smoke: {len(surf.rows)} rows "
        f"({len(infeasible)} infeasible, reason recorded), "
        f"{surf.stats['n_trajectories']} simulated runs at "
        f"{surf.stats['runs_per_sec']} runs/s"
    )

    # 2) the event log validates, and carries every engine phase
    errors = obs_events.validate_file(events_path)
    assert not errors, "\n".join(errors)
    with open(events_path) as f:
        kinds = [
            rec.get("kind")
            for rec in map(json.loads, f)
            if rec.get("type") == "whatif"
        ]
    assert "grid" in kinds and "surface" in kinds
    assert kinds.count("point") == len(surf.rows)
    print(f"whatif-smoke: events validate ({len(kinds)} whatif records)")

    # 3) adapt priors round-trip: reload the artifact, seed the bandit,
    # and the first decision exploits instead of warm-up-exploring
    reloaded = Surface.load(run_dir)
    arms = [
        adapt.Arm("naive"),
        adapt.Arm("avoidstragg"),
        adapt.Arm("approx", num_collect=4),
    ]
    priors = reloaded.adapt_priors(arms, n_workers=6, n_stragglers=1)
    assert set(priors) == {a.label for a in arms}, priors
    ctl = adapt.AdaptiveController(
        arms, adapt.ControllerConfig(seed=0), priors=priors
    )
    idx, reason = ctl.choose()
    assert reason == "exploit", (reason, priors)
    cold = adapt.AdaptiveController(arms, adapt.ControllerConfig(seed=0))
    assert cold.choose()[1] == "warmup"
    print(
        f"whatif-smoke: priors prime {len(priors)} arms; first primed "
        f"decision = {arms[idx].label} [exploit] (cold start would "
        "burn a warm-up pass)"
    )

    # 4) serve ETA round-trip: the daemon quotes the surface's expected
    # time-to-target on an accepted request before any dispatch
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.serve.server import SweepServer
    from erasurehead_tpu.utils.config import RunConfig

    cfg = RunConfig(
        scheme="approx", n_workers=6, n_stragglers=1, num_collect=4,
        rounds=12, n_rows=96, n_cols=8, lr_schedule=1.0, add_delay=True,
        compute_mode="deduped", update_rule="GD", seed=0,
    )
    ds = generate_gmm(96, 8, 6, seed=0)
    with SweepServer(eta_surface=reloaded) as srv:
        h = srv.submit(tenant="smoke", label="agc", config=cfg, dataset=ds)
        eta = h.eta_s
        res = h.result(timeout=300)
    expected = reloaded.eta(cfg)
    assert eta is not None and eta > 0, eta
    assert eta == expected, (eta, expected)
    assert res.status == "ok", res
    print(f"whatif-smoke: serve quoted eta_s={eta} on an accepted request")

    # 5a) rerun with the artifact present: rehydrates (no re-simulation),
    # identical rows object
    with obs_events.capture(os.path.join(OUT, "events_rerun.jsonl")):
        rehydrated = run_whatif(spec, out_dir=run_dir)
    assert rehydrated.stats is None  # loaded, not simulated
    assert rehydrated.rows == surf.rows
    with open(os.path.join(OUT, "events_rerun.jsonl")) as f:
        rr_kinds = [
            rec.get("kind")
            for rec in map(json.loads, f)
            if rec.get("type") == "whatif"
        ]
    assert rr_kinds == ["rehydrate"], rr_kinds

    # 5b) forced re-simulation into a fresh dir: both artifact files are
    # byte-identical — the bitwise-rehydration contract at file level
    rerun_dir = os.path.join(OUT, "surface_rerun")
    run_whatif(spec, out_dir=rerun_dir, rehydrate=False)
    for name in ("surface_rows.jsonl", "surface.npz"):
        a = _file_bytes(os.path.join(run_dir, name))
        b = _file_bytes(os.path.join(rerun_dir, name))
        assert a == b, f"{name} differs between identical-spec runs"
    print("whatif-smoke: identical spec rehydrates bitwise (jsonl + npz)")
    print("whatif-smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
