#!/usr/bin/env python
"""Train the canonical scheme comparison on REAL (non-synthetic) data.

The four reference datasets need network access (Kaggle CSVs / sklearn
fetch), which this sandbox does not have; scikit-learn's bundled UCI
breast-cancer set is genuinely real clinical data, so it stands in to
prove the full preparer -> partition -> coded-training -> eval pipeline on
non-synthetic value distributions (VERDICT r2 item 5). Writes
artifacts/6_agc_breast_cancer[real-uci].{json,png}.

Usage: python tools/real_data_run.py [--rounds 60] [--out-dir artifacts]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=60)
    ap.add_argument("--out-dir", default="artifacts")
    ap.add_argument("--workers", type=int, default=12)
    ns = ap.parse_args()

    from erasurehead_tpu.data import real
    from erasurehead_tpu.train import experiments, plots
    from erasurehead_tpu.utils.config import RunConfig

    ds = real.prepare("breast_cancer", input_dir=None)
    n_train, n_feat = ds.X_train.shape
    print(
        f"breast_cancer (real UCI): train {ds.X_train.shape}, "
        f"test {ds.X_test.shape}, nnz/row "
        f"{ds.X_train.nnz / n_train:.1f}",
        file=sys.stderr,
    )

    W = ns.workers
    base = dict(
        n_workers=W, rounds=ns.rounds, add_delay=True,
        n_rows=n_train, n_cols=n_feat, update_rule="AGD",
        lr_schedule=1.0, seed=0,
    )
    configs = {
        "naive": RunConfig(scheme="naive", n_stragglers=0, **base),
        "cyccoded_s2": RunConfig(scheme="cyccoded", n_stragglers=2, **base),
        "agc_collect_N-3": RunConfig(
            scheme="approx", n_stragglers=2, num_collect=W - 3, **base
        ),
        "avoidstragg_s2": RunConfig(
            scheme="avoidstragg", n_stragglers=2, **base
        ),
    }
    summaries = experiments.compare(configs, ds)
    print(experiments.format_table(summaries))

    os.makedirs(ns.out_dir, exist_ok=True)
    stem = os.path.join(ns.out_dir, "6_agc_breast_cancer[real-uci]")
    experiments.save_summaries(summaries, stem + ".json")
    fig = plots.save_comparison_figure(
        summaries, stem + ".png", title="breast_cancer (real UCI data)"
    )
    print(f"artifacts -> {stem}.json" + (f", {fig}" if fig else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
