#!/usr/bin/env python
"""Train the canonical scheme comparisons on REAL (non-synthetic) data.

The four reference datasets need network access (Kaggle CSVs / sklearn
fetch), which this sandbox does not have; scikit-learn's bundled UCI sets
are genuinely real, so they stand in to prove the full preparer ->
partition -> coded-training -> eval pipeline on non-synthetic value
distributions (VERDICT r2 item 5): breast_cancer for the logistic family
and diabetes for the linear (least-squares) family, mirroring the
reference's covtype and kc_house_data configs. Writes
artifacts/6_agc_breast_cancer[real-uci].{json,png} and
artifacts/7_agc_linear_diabetes[real-uci].{json,png}.

Usage: python tools/real_data_run.py [--rounds 60] [--out-dir artifacts]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=60)
    ap.add_argument("--out-dir", default="artifacts")
    ap.add_argument("--workers", type=int, default=12)
    ns = ap.parse_args()

    from erasurehead_tpu.data import real
    from erasurehead_tpu.train import experiments, plots
    from erasurehead_tpu.utils.config import RunConfig

    W = ns.workers
    os.makedirs(ns.out_dir, exist_ok=True)

    def run_comparison(dataset_name, stem_name, title, scheme_specs, **cfg_kw):
        """prepare -> compare -> table -> save: one home for both runs."""
        ds = real.prepare(dataset_name, input_dir=None)
        n_train, n_feat = ds.X_train.shape
        print(
            f"{dataset_name} (real UCI): train {ds.X_train.shape}, "
            f"test {ds.X_test.shape}, nnz/row "
            f"{ds.X_train.nnz / n_train:.1f}",
            file=sys.stderr,
        )
        base = dict(
            n_workers=W, rounds=ns.rounds, add_delay=True,
            n_rows=n_train, n_cols=n_feat, update_rule="AGD", seed=0,
            **cfg_kw,
        )
        configs = {
            label: RunConfig(**{**base, **spec})
            for label, spec in scheme_specs.items()
        }
        summaries = experiments.compare(configs, ds)
        print(experiments.format_table(summaries))
        stem = os.path.join(ns.out_dir, stem_name)
        experiments.save_summaries(summaries, stem + ".json")
        fig = plots.save_comparison_figure(summaries, stem + ".png",
                                           title=title)
        print(f"artifacts -> {stem}.json" + (f", {fig}" if fig else ""))

    # logistic family on real clinical data (≙ the reference's covtype
    # config, arrange_real_data.py:145-205)
    run_comparison(
        "breast_cancer", "6_agc_breast_cancer[real-uci]",
        "breast_cancer (real UCI data)",
        {
            "naive": dict(scheme="naive", n_stragglers=0),
            "cyccoded_s2": dict(scheme="cyccoded", n_stragglers=2),
            "agc_collect_N-3": dict(
                scheme="approx", n_stragglers=2, num_collect=W - 3
            ),
            "avoidstragg_s2": dict(scheme="avoidstragg", n_stragglers=2),
        },
        lr_schedule=1.0,
    )

    # linear family on real regression data (≙ the reference's
    # kc_house_data least-squares config, run_approx_coding.sh:31-36)
    run_comparison(
        "diabetes", "7_agc_linear_diabetes[real-uci]",
        "diabetes linear regression (real UCI data)",
        {
            "naive": dict(scheme="naive", n_stragglers=0),
            "agc_collect_N-3": dict(
                scheme="approx", n_stragglers=2, num_collect=W - 3
            ),
        },
        model="linear", lr_schedule=0.1,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
