"""Evaluate the open lowering decisions against tools/measurements.jsonl.

Each production default flips ONLY on a measured end-to-end win (the
repo's measurement discipline; profile wins do not transfer — the dense
flat margin won its profile and lost the step race). This tool encodes the
round-4 decision table (VERDICT r3 items 1-2) so a healthy relay window is
followed by mechanical default flips:

  dense  — MARGIN_FLAT_DEFAULT (parallel/step.py): dense_f32_marginflat
           races the captured dense_f32 per-slot baseline (and the
           margincols8 candidate, which also remains un-defaulted).
  fields — the FieldOnehot production constellation (sparse_lanes /
           fields_margin / fields_scatter under the flat lowering):
           best of {flat, lanes8_flat, lanes8_onehot_flat, mxu_flat}
           per shape; a default flips only if the same candidate wins
           BOTH canonical shapes, else the winners are reported per
           shape for a shape-conditional default.
  deduped — whether deduped mode routes FieldOnehot through the same
           constellation (deduped_fields_* vs the padded per-slot
           deduped baselines).

Usage: python tools/harvest_decisions.py [tools/measurements.jsonl]
Prints a markdown digest; exits 0 always (missing entries are reported,
not fatal — the sweep is resumable).
"""

from __future__ import annotations

import json
import sys


def load(path: str) -> dict[str, dict]:
    out: dict[str, dict] = {}
    try:
        for ln in open(path):
            if not ln.strip():
                continue
            e = json.loads(ln)
            out[e["tag"]] = e.get("result", {})
    except FileNotFoundError:
        pass
    return out


#: repeat-capture suffixes (VERDICT r5 #5): `_rep` from the flat program's
#: headline repeats, `_rep2` from tools/tpu_measurements_rep2.sh. A
#: decision is marked n>=2 only when its winner AND baseline each have at
#: least two captures; n=1 decisions print as provisional.
REP_SUFFIXES = ("", "_rep", "_rep2")


def val(entries, tag):
    r = entries.get(tag)
    return None if r is None else r.get("value")


def captures(entries, tag):
    """All captured values for ``tag`` across the repeat suffixes."""
    return [
        v
        for suf in REP_SUFFIXES
        if (v := val(entries, tag + suf)) is not None
    ]


def best(entries, tags):
    have = [(t, val(entries, t)) for t in tags if val(entries, t) is not None]
    missing = [t for t in tags if val(entries, t) is None]
    have.sort(key=lambda tv: -tv[1])
    return have, missing


def _rep_note(entries, tag):
    vals = captures(entries, tag)
    if len(vals) <= 1:
        return " [n=1 — repeat missing]" if vals else ""
    return f" [n={len(vals)}, spread {min(vals)}-{max(vals)}]"


def decision_n(entries, *tags):
    """min capture count across the tags a decision rests on."""
    return min((len(captures(entries, t)) for t in tags), default=0)


def section(entries, title, tags, extra=None):
    """Print one decision section: each tag's value or MISSING (with its
    repeat count/spread), then the current winner annotated with the
    decision's n. Returns (have, missing) for any follow-up rule."""
    have, missing = best(entries, tags)
    print(f"\n## {title}\n")
    for t, v in have:
        line = f"- {t}: {v} steps/s"
        if extra:
            line += f" (vs_baseline {entries.get(t, {}).get('vs_baseline')})"
        print(line + _rep_note(entries, t))
    for t in missing:
        print(f"- {t}: MISSING")
    if have:
        n = decision_n(entries, have[0][0], tags[0])
        strength = (
            f"n>={n}" if n >= 2 else "PROVISIONAL n=1 — run "
            "tools/tpu_measurements_rep2.sh before flipping a default"
        )
        print(f"\n=> current winner: {have[0][0]} at {have[0][1]} steps/s"
              f" ({strength})"
              + (" (entries still missing)" if missing else ""))
    return have, missing


def main() -> None:
    path = sys.argv[1] if len(sys.argv) > 1 else "tools/measurements.jsonl"
    e = load(path)

    print("# Harvest decision digest\n")

    # --- dense margin ------------------------------------------------------
    have, missing = section(
        e, "dense margin lowering (MARGIN_FLAT_DEFAULT, step.py)",
        ["dense_f32", "dense_f32_margincols8", "dense_f32_marginflat"],
    )
    if have and not missing:
        winner, base = have[0][0], val(e, "dense_f32")
        n = decision_n(e, winner, "dense_f32")
        tag = f"n>={n}" if n >= 2 else "PROVISIONAL n=1"
        if winner == "dense_f32_marginflat" and have[0][1] > base:
            print(f"=> FLIP MARGIN_FLAT_DEFAULT=True ({have[0][1]} > {base}; "
                  f"{tag})")
        else:
            print(f"=> keep per-slot defaults; winner is {winner} ({tag})")
    else:
        print("=> UNDECIDED (entries missing)")

    section(e, "dense bf16 frontier",
            ["dense_bf16", "dense_bf16_flat", "dense_bf16_marginflat"])

    # ring stack mode (stack_mode="ring", this round): the default is
    # footprint-gated (sharding.RING_AUTO_MIN_BYTES), not race-gated —
    # these captures price the per-round ppermute hops against the
    # materialized baseline and carry the on-silicon stack_bytes /
    # memory_analysis evidence for the (s+1)x claim
    section(e, "ring-streamed faithful stack (stack_mode, informational)",
            ["dense_f32", "dense_f32_ring", "dense_bf16_ring"])

    # scan unroll: the in-scan bandwidth-gap candidate (r5). A winner
    # here composes with whatever margin lowering wins above — decide
    # the unroll default, then re-race the composed form if both win.
    section(e, "dense scan unroll (cfg.scan_unroll)",
            ["dense_f32", "dense_f32_unroll4", "dense_f32_unroll8"])

    for shape in ("covtype", "amazon"):
        section(
            e, f"faithful {shape} fields constellation",
            [
                f"sparse_{shape}_faithful_fields_flat",
                f"sparse_{shape}_faithful_fields_lanes8_flat",
                f"sparse_{shape}_faithful_fields_lanes8_onehot_flat",
                f"sparse_{shape}_faithful_fields_mxu_flat",
            ],
            extra=True,
        )

    for shape in ("covtype", "amazon"):
        section(
            e, f"deduped {shape}",
            [
                f"sparse_{shape}_deduped",
                f"sparse_{shape}_deduped_fields",
                f"sparse_{shape}_deduped_fields_flat",
                f"sparse_{shape}_deduped_fields_lanes8_flat",
                f"sparse_{shape}_deduped_fields_mxu_flat",
            ],
        )

    # --- evidence entries (round-4/5; no default gates on these) ----------
    print("\n## evidence entries\n")
    for tag in ("measured_arrival_agc", "dense_hbm_crosscheck",
                "dynamic_mds_w30_10k"):
        r = e.get(tag)
        print(f"- {tag}: " + ("MISSING" if r is None else json.dumps(r)[:300]))

    # --- repeat captures (VERDICT r4 #8 / r5 #5: window variance for every
    # headline number; tpu_measurements_rep2.sh feeds the _rep2 column) -----
    print("\n## headline repeats (window variance)\n")
    for base_tag in ("sparse_covtype_faithful_fields_flat",
                     "sparse_amazon_faithful_fields_flat",
                     "sparse_covtype_faithful",
                     "sparse_amazon_faithful",
                     "dense_f32",
                     "dense_f32_ring"):
        vals = captures(e, base_tag)
        if not vals:
            print(f"- {base_tag}: MISSING")
            continue
        spread = (
            f", spread {min(vals)}-{max(vals)} steps/s"
            if len(vals) > 1
            else " — repeat missing (tpu_measurements_rep2.sh)"
        )
        print(f"- {base_tag}: n={len(vals)} ({', '.join(map(str, vals))})"
              f"{spread}")


if __name__ == "__main__":
    main()
