"""Measured-arrival AGC on real silicon (VERDICT r3 #5).

Runs ``trainer.train_measured`` — the mode where per-worker arrival times
are REAL device timings, not the simulated schedule — at a modest shape
with ``--n-slow`` work-multiplied slow workers, making ``worker_timeset``
a silicon measurement (≙ the reference's Waitany arrival stamps,
src/naive.py:106). The same measured protocol is then replayed under the
naive all-workers rule: the AGC/naive protocol-rate ratio is the paper's
straggler-tolerance claim measured with real (induced) compute
heterogeneity instead of injected sleeps.

Prints one JSON line (measure_lib contract: exit 0, last line JSON with a
"platform" key); on TPU also writes the full measured artifact
(worker_times, timeset, collected) to artifacts/measured_arrival_tpu.json.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=12)
    ap.add_argument("--stragglers", type=int, default=2)
    ap.add_argument("--num-collect", type=int, default=8)
    ap.add_argument("--rows", type=int, default=12 * 4096)
    ap.add_argument("--cols", type=int, default=512)
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument(
        "--mult", type=int, default=8000,
        help="work multiplier for the slow workers (fori_loop INSIDE one "
        "dispatch — real device compute, not dispatch overhead)",
    )
    ap.add_argument("--n-slow", type=int, default=2)
    ap.add_argument("--light", action="store_true",
                    help="rehearsal shape (CPU: seconds, not minutes)")
    args = ap.parse_args()
    if args.light:
        args.rows, args.cols = 12 * 64, 32
        args.rounds, args.mult = 3, 50

    import jax

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    platform = jax.devices()[0].platform
    W, n_slow = args.workers, args.n_slow
    mult = np.ones(W, np.int64)
    mult[:n_slow] = args.mult
    print(
        f"bench_measured: platform={platform} W={W} rows={args.rows} "
        f"cols={args.cols} rounds={args.rounds} mult={args.mult}x{n_slow}",
        file=sys.stderr,
    )
    data = generate_gmm(args.rows, args.cols, n_partitions=W, seed=0)

    def cfg(scheme, **kw):
        return RunConfig(
            scheme=scheme, n_workers=W, n_stragglers=args.stragglers,
            rounds=args.rounds, n_rows=args.rows, n_cols=args.cols,
            lr_schedule=1.0, update_rule="AGD", add_delay=False, seed=0,
            **kw,
        )

    t0 = time.perf_counter()
    agc = trainer.train_measured(
        cfg("approx", num_collect=args.num_collect), data,
        work_multiplier=mult,
    )
    # same measured protocol, wait-for-all rule: the baseline denominator.
    # worker_msg executables are shape-identical, so compiles are reused.
    naive = trainer.train_measured(cfg("naive"), data, work_multiplier=mult)
    total = time.perf_counter() - t0

    agc_rate = args.rounds / agc.sim_total_time
    naive_rate = args.rounds / naive.sim_total_time
    # naive collects everyone, so its worker_times carry no -1 sentinels:
    # the honest per-worker compute record for slow/fast attribution
    slow_ms = float(np.median(naive.worker_times[:, :n_slow])) * 1e3
    fast_ms = float(np.median(naive.worker_times[:, n_slow:])) * 1e3
    slow_excluded = (agc.worker_times[:, :n_slow] == -1.0).all(axis=1)
    hist = np.asarray(agc.params_history)
    finite = bool(np.isfinite(hist).all())

    result = {
        "metric": "AGC_measured_arrival_protocol_steps_per_sec",
        "value": round(agc_rate, 3),
        "unit": "iterations/sec",
        # AGC's protocol-rate advantage over wait-for-all under the SAME
        # measured arrivals — the straggler-tolerance claim on silicon
        "vs_baseline": round(agc_rate / naive_rate, 3),
        "platform": platform,
        "naive_protocol_steps_per_sec": round(naive_rate, 3),
        "wall_steps_per_sec": round(agc.steps_per_sec, 3),
        "slow_excluded_frac": round(float(slow_excluded.mean()), 3),
        "slow_ms_median": round(slow_ms, 3),
        "fast_ms_median": round(fast_ms, 3),
        "finite": finite,
        "rounds": args.rounds,
        "mult": args.mult,
        "wall_total_s": round(total, 1),
    }
    print(
        f"bench_measured: agc={agc_rate:.2f} it/s naive={naive_rate:.2f} "
        f"it/s ratio={agc_rate / naive_rate:.2f} slow={slow_ms:.1f}ms "
        f"fast={fast_ms:.1f}ms excluded={slow_excluded.mean():.2f}",
        file=sys.stderr,
    )
    if platform == "tpu":
        art = {
            "config": {
                "workers": W, "stragglers": args.stragglers,
                "num_collect": args.num_collect, "rows": args.rows,
                "cols": args.cols, "rounds": args.rounds,
                "mult": args.mult, "n_slow": n_slow,
            },
            "platform": platform,
            "agc": {
                "worker_timeset": agc.worker_times.tolist(),
                "timeset": agc.timeset.tolist(),
                "collected": agc.collected.tolist(),
            },
            "naive": {
                "worker_timeset": naive.worker_times.tolist(),
                "timeset": naive.timeset.tolist(),
                "collected": naive.collected.tolist(),
            },
            "summary": result,
        }
        out = pathlib.Path(__file__).resolve().parent.parent / "artifacts"
        out.mkdir(exist_ok=True)
        (out / "measured_arrival_tpu.json").write_text(
            json.dumps(art, indent=1)
        )
        print(
            f"bench_measured: artifact -> {out / 'measured_arrival_tpu.json'}",
            file=sys.stderr,
        )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
