"""Canonical-scale SPARSE benchmark: covtype-shaped AGC on real TPU.

VERDICT r1 item 4: the reference's actual flagship workload is sparse one-hot
covtype — 396112 rows x 15509 one-hot columns (run_approx_coding.sh:26-28,
src/arrange_real_data.py:145-205) — and round 1 never ran the PaddedRows
path at that scale. This runs the AGC trainer on a covtype-shaped synthetic
one-hot CSR dataset (identical structure: nnz_per_row=12, 15509 categories;
the Kaggle/UCI raws are absent in this environment) at the canonical
W=30 / s=2 / collect=15 / AGD / 100-round configuration, on whatever
accelerator is live, and prints ONE JSON line with steps/sec.

Usage: python tools/bench_sparse.py [--rows 396090] [--cols 15509] [--light]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

ROUNDS = 100
W, S, COLLECT = 30, 2, 15


def main() -> None:
    ap = argparse.ArgumentParser()
    # canonical rows are trimmed to a multiple of W (the reference's
    # integer division drops the remainder rows the same way, coded.py:23)
    ap.add_argument(
        "--shape", default="covtype", choices=["covtype", "amazon"],
        help="canonical dataset shape preset (run_approx_coding.sh:26-36): "
             "covtype 396112x15509 nnz=12, amazon 26210x241915 nnz=44",
    )
    ap.add_argument("--rows", type=int, default=None)
    ap.add_argument("--cols", type=int, default=None)
    ap.add_argument("--nnz", type=int, default=None)
    ap.add_argument("--rounds", type=int, default=None)
    ap.add_argument(
        "--light", action="store_true",
        help="1/30-scale smoke run (CI / CPU)",
    )
    ap.add_argument(
        "--mode", default="faithful", choices=["faithful", "deduped"],
        help="deduped computes each partition once (the framework's "
             "optimization; bit-comparable gradients, 1/(s+1) the lookups)",
    )
    ap.add_argument(
        "--lanes", type=int, default=None,
        help="sparse margin-gather lane width (power of two); applies to "
             "PaddedRows value gathers and FieldOnehot pair-table gathers",
    )
    ap.add_argument(
        "--format", dest="sparse_format", default="padded",
        choices=["padded", "fields", "auto"],
        help="fields = FieldOnehot fused pair-table lowering (halves the "
             "lookup count on one-hot field-structured data)",
    )
    ap.add_argument(
        "--flat", dest="flat_grad", default="auto",
        choices=["auto", "on", "off"],
        help="flat-stack closed-form lowering (step.make_flat_grad_fn): "
             "one scatter accumulator instead of a vmapped per-slot batch",
    )
    ap.add_argument(
        "--fields-scatter", default="pairs", choices=["pairs", "onehot"],
        help="FieldOnehot gradient-scatter lowering: onehot = per-field "
             "one-hot MXU matmuls instead of pair-accumulator scatter-adds",
    )
    ap.add_argument(
        "--fields-margin", default="tables", choices=["tables", "onehot"],
        help="FieldOnehot margin lowering: onehot = per-field one-hot MXU "
             "matmuls instead of pair-table gathers (lanes ignored)",
    )
    args = ap.parse_args()
    presets = {
        "covtype": (396112 // W * W, 15509, 12),
        "amazon": (26210 // W * W, 241915, 44),
    }
    rows0, cols0, nnz0 = presets[args.shape]
    rounds0 = ROUNDS
    if args.light:  # shrink the DEFAULTS only: explicit flags still win
        rows0, cols0, rounds0 = rows0 // 30 // W * W, cols0 // 10, 10
    args.rows = args.rows if args.rows is not None else rows0
    args.cols = args.cols if args.cols is not None else cols0
    args.nnz = args.nnz if args.nnz is not None else nnz0
    args.rounds = args.rounds if args.rounds is not None else rounds0

    import jax

    from erasurehead_tpu.data.synthetic import generate_onehot
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    platform = jax.devices()[0].platform
    print(
        f"bench_sparse: platform={platform} rows={args.rows} "
        f"cols={args.cols} nnz={args.nnz} W={W} s={S} collect={COLLECT} "
        f"rounds={args.rounds}",
        file=sys.stderr,
    )

    t0 = time.perf_counter()
    data = generate_onehot(
        args.rows, args.cols, n_partitions=W, n_fields=args.nnz, seed=0
    )
    print(
        f"bench_sparse: generated CSR in {time.perf_counter() - t0:.1f}s "
        f"(nnz={data.X_train.nnz})",
        file=sys.stderr,
    )
    if args.sparse_format == "auto":
        # resolve now so the recorded format and the traffic model describe
        # the representation that actually ran; an explicit --lanes pins
        # padded (RunConfig applies the same rule). The inference repeats
        # inside partition_stack — accepted: it is sub-second against a
        # benchmark run measured in minutes, and keeps this resolution
        # honest on the exact matrix being trained.
        if args.lanes is not None:
            args.sparse_format = "padded"
        else:
            from erasurehead_tpu.ops.features import infer_field_sizes

            args.sparse_format = (
                "fields" if infer_field_sizes(data.X_train) is not None
                else "padded"
            )
        print(
            f"bench_sparse: --format auto -> {args.sparse_format}",
            file=sys.stderr,
        )

    cfg = RunConfig(
        scheme="approx",
        n_workers=W,
        n_stragglers=S,
        num_collect=COLLECT,
        rounds=args.rounds,
        n_rows=args.rows,
        n_cols=args.cols,
        update_rule="AGD",
        # lr_schedule=None -> the shape's own dataset preset (main.py:37-46;
        # amazon's canonical lr is 100x covtype's)
        dataset=args.shape,
        add_delay=True,
        compute_mode=args.mode,
        sparse_lanes=args.lanes,
        sparse_format=args.sparse_format,
        flat_grad=args.flat_grad,
        fields_scatter=args.fields_scatter,
        fields_margin=args.fields_margin,
        seed=0,
    )
    t0 = time.perf_counter()
    result = trainer.train(cfg, data)
    total = time.perf_counter() - t0

    steps_per_sec = result.steps_per_sec
    ref_rate = args.rounds / result.sim_total_time
    # HBM traffic model for the PaddedRows step: per nonzero, each pass
    # moves a 4-byte index plus the value payload — 4 bytes scalar, or an
    # L-lane row (4*L bytes) under --lanes (that traffic amplification is
    # the lowering's explicit trade, ops/features.py). Two passes per step
    # (margin gather + scatter accumulate); beta gathers are absorbed in
    # the same pass. Deduped mode touches each partition once instead of
    # (s+1) redundant slots.
    slot_rows = args.rows // W
    n_stacks = W * (S + 1) if args.mode == "faithful" else W
    if args.sparse_format == "fields":
        # FieldOnehot stores only the [rows, K] int32 locals (no value
        # payload); pair tables are rebuilt per step but are tiny vs the
        # row traffic and are excluded from this stack-traffic model.
        stack_bytes = n_stacks * slot_rows * args.nnz * 4
        bytes_per_step = 2 * stack_bytes  # margin gather + scatter passes
    else:
        # Two passes with asymmetric payloads: lanes apply to the margin
        # gather only (the scatter stays scalar — rmatvec ignores the
        # knob, ops/features.py), so the margin pass moves 4-byte index +
        # 4L-byte lane row per nnz while the scatter pass moves 4 + 4.
        margin_payload = 4 * (args.lanes or 1)
        bytes_per_step = n_stacks * slot_rows * args.nnz * (
            (4 + margin_payload) + (4 + 4)
        )
    if args.sparse_format == "fields" and args.lanes:
        # Lane terms, margin pass only (the scatter stays scalar): one
        # L-lane table read per plan entry per row, plus the per-step
        # [entries, L] replicated-table build (written once behind the
        # barrier; beta changes every step so it cannot be hoisted — at
        # lane widths this is no longer "tiny vs the row traffic"). The
        # plan is lane-aware — fields whose replicated pair table would
        # blow the lane budget fall back to singles (e.g. every amazon
        # field) — so both terms come from the actual plan, not an
        # all-pairs assumption.
        from erasurehead_tpu.ops.features import (
            fields_margin_plan, infer_field_sizes,
        )

        sizes = infer_field_sizes(data.X_train)
        if sizes is None:  # unreachable: fields mode validated the data
            sizes = (args.cols // args.nnz,) * args.nnz
        plan = fields_margin_plan(sizes, args.lanes)
        table_entries = sum(
            sizes[e[1]] * sizes[e[2]] if e[0] == "pair" else sizes[e[1]]
            for e in plan
        )
        # the table build is once per step in BOTH lowerings: under the
        # per-slot vmap ("off") the tables are built from the unbatched
        # params, so vmap's batching rules leave them slot-invariant —
        # verified from the jaxpr (one [entries, L] broadcast+barrier
        # OUTSIDE the batched inner jaxpr; the round-3 vmap catastrophe
        # was the batched BACKWARD scatter accumulators, not these)
        bytes_per_step += (
            n_stacks * slot_rows * len(plan) * 4 * args.lanes
            + table_entries * 4 * args.lanes
        )
    achieved_gbps = bytes_per_step * steps_per_sec / 1e9

    print(
        f"bench_sparse: wall(total incl. compile)={total:.1f}s "
        f"scan={result.wall_time:.3f}s ours={steps_per_sec:.1f} it/s "
        f"ref_rate={ref_rate:.3f} it/s achieved={achieved_gbps:.1f} GB/s",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": (
                    f"AGC_logistic_sparse_{args.shape}_shape_steps_per_sec"
                    f"{'_light' if args.light else ''}"
                ),
                "value": round(float(steps_per_sec), 3),
                "unit": "iterations/sec",
                "vs_baseline": round(float(steps_per_sec / ref_rate), 3),
                "platform": platform,
                "mode": args.mode,
                "lanes": args.lanes,
                "format": args.sparse_format,
                "flat": args.flat_grad,
                "fields_scatter": args.fields_scatter,
                "fields_margin": args.fields_margin,
                "n_rows": args.rows,
                "n_cols": args.cols,
                "nnz_per_row": args.nnz,
                "wall_time_s": round(float(result.wall_time), 4),
                "bytes_per_step": bytes_per_step,
                "achieved_gbps": round(float(achieved_gbps), 2),
            }
        )
    )


if __name__ == "__main__":
    main()
