"""Summarize a tools/tpu_measurements.sh JSONL file into a markdown table.

Usage: python tools/summarize_measurements.py [tools/measurements.jsonl]

Groups the tagged entries: benches (steps/sec + vs_baseline + bandwidth),
profiles (per-variant milliseconds), and the kernel race — the digest that
goes into BASELINE.md's "Measured results" after a sweep.
"""

from __future__ import annotations

import json
import sys


def main() -> None:
    path = sys.argv[1] if len(sys.argv) > 1 else "tools/measurements.jsonl"
    try:
        lines = [
            json.loads(ln)
            for ln in open(path)
            if ln.strip()
        ]
    except FileNotFoundError:
        print(f"no measurements at {path}; run tools/tpu_measurements.sh")
        return
    except json.JSONDecodeError as e:
        print(f"corrupt line in {path}: {e}")
        return

    benches, profiles, races = [], [], []
    for entry in lines:
        tag, res = entry.get("tag", "?"), entry.get("result", {})
        if "value" in res:
            benches.append((tag, res))
        elif {"logistic", "linear"} & res.keys():
            races.append((tag, res))
        else:
            profiles.append((tag, res))

    if benches:
        print("## Benches (steps/sec)\n")
        print("| tag | platform | value | vs_baseline | GB/s | extras |")
        print("|---|---|---|---|---|---|")
        for tag, r in benches:
            extras = ", ".join(
                f"{k}={r[k]}"
                for k in (
                    "mode", "lanes", "format", "flat", "dtype",
                    "pct_roofline",
                )
                if r.get(k) is not None
            )
            print(
                f"| {tag} | {r.get('platform')} | {r.get('value')} "
                f"| {r.get('vs_baseline')} | {r.get('achieved_gbps', '')} "
                f"| {extras} |"
            )
        print()

    for tag, r in races:
        print(f"## Kernel race ({tag}, platform={r.get('platform')})\n")
        for kind in ("logistic", "linear"):
            if kind in r:
                k = r[kind]
                flag = (
                    f"  INVALID: {k['invalid']}" if k.get("invalid") else ""
                )
                print(
                    f"- {kind}: pallas {k.get('pallas_ms')}ms vs "
                    f"XLA {k.get('xla_ms')}ms (speedup {k.get('speedup')})"
                    f"{flag}"
                )
        print()

    for tag, r in profiles:
        ms = {k: v for k, v in r.items() if k.endswith("_ms")}
        if not ms:
            continue
        print(f"## Profile ({tag}, platform={r.get('platform')}, "
              f"shape={r.get('shape')})\n")
        best = min(ms, key=ms.get)
        for k, v in sorted(ms.items(), key=lambda kv: kv[1]):
            mark = "  <- fastest" if k == best else ""
            print(f"- {k[:-3]}: {v} ms{mark}")
        print()


if __name__ == "__main__":
    main()
