#!/usr/bin/env python
"""Smoke-check the memory-system roofline levers on CPU
(`make roofline-smoke`).

Runs a small ring + pipelined + int8 sweep and asserts the MECHANISM of
each ISSUE-6 lever (the TPU step-time numbers come from the tagged
measurement program; this asserts what must hold on any backend):

  - f32 bitwise pins: materialized == ring == ring+pipelined trajectories
    for the flagship scheme shape, with donation on;
  - bytes accounting, exactly: the ring stack is 1/(s+1) of the
    materialized stack, and the int8 ring stack's payload is 1/4 of the
    f32 ring stack's (plus the scale table + labels, computed here to the
    byte);
  - dispatch counts: the int8+ring+pipelined 2-scheme x 2-seed cohort is
    ONE dispatch (cohort.dispatches counter), and a rerun of every
    variant is pure cache hits (no recompiles, no re-uploads);
  - no donated buffer is a cached device array (the data cache's pins
    are alive after every donating dispatch).

Exit 0 = all assertions hold; 1 = failure (printed).
"""

import os
import sys

# runnable from anywhere without an install (the tools/ convention)
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU relay


def main() -> int:
    import dataclasses

    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.obs.metrics import REGISTRY
    from erasurehead_tpu.train import cache, trainer
    from erasurehead_tpu.utils.config import RunConfig

    failures = []

    def check(ok, msg):
        if not ok:
            failures.append(msg)

    def bitwise(a, b):
        la, lb = jax.tree.leaves(a.params_history), jax.tree.leaves(
            b.params_history
        )
        return len(la) == len(lb) and all(
            np.array_equal(np.asarray(x), np.asarray(y))
            for x, y in zip(la, lb)
        )

    # FRC shape with real (s+1)=3x redundancy — the ring lever's subject
    W, s, rows_per, F, R = 12, 2, 16, 24, 4
    data = generate_gmm(W * rows_per, F, n_partitions=W, seed=0)
    base = RunConfig(
        scheme="repcoded", n_workers=W, n_stragglers=s, rounds=R,
        n_rows=W * rows_per, n_cols=F, lr_schedule=0.5,
        update_rule="AGD", add_delay=True, seed=0, donate="on",
    )
    cache.clear()

    # ---- lever 1+2: ring + pipelined transport, donation on ------------
    m = trainer.train(base, data)
    r = trainer.train(dataclasses.replace(base, stack_mode="ring"), data)
    p = trainer.train(
        dataclasses.replace(
            base, stack_mode="ring", ring_pipeline="on"
        ),
        data,
    )
    check(bitwise(m, r), "f32 ring != materialized (bitwise pin broken)")
    check(bitwise(m, p), "f32 ring+pipelined != materialized")
    check(
        p.cache_info["ring_pipeline"] == "pipelined",
        f"expected pipelined transport, got {p.cache_info['ring_pipeline']}",
    )
    check(
        m.cache_info["donation"] is True,
        "donation did not resolve on",
    )

    # bytes accounting, to the byte: materialized = (s+1) x ring
    x_ring = W * rows_per * F * 4
    y_ring = W * rows_per * 4
    check(
        r.cache_info["stack_bytes"] == x_ring + y_ring,
        f"ring f32 stack bytes {r.cache_info['stack_bytes']} != "
        f"{x_ring + y_ring}",
    )
    check(
        m.cache_info["stack_bytes"] == (s + 1) * r.cache_info["stack_bytes"],
        f"materialized {m.cache_info['stack_bytes']} != "
        f"{s + 1}x ring {r.cache_info['stack_bytes']}",
    )

    # ---- lever 3: int8 compressed stack over the pipelined ring --------
    q = trainer.train(
        dataclasses.replace(
            base, stack_mode="ring", ring_pipeline="on", stack_dtype="int8"
        ),
        data,
    )
    x_q = W * rows_per * F * 1
    scale_q = W * F * 4
    check(
        q.cache_info["stack_bytes"] == x_q + scale_q + y_ring,
        f"int8 ring stack bytes {q.cache_info['stack_bytes']} != "
        f"{x_q + scale_q + y_ring}",
    )
    check(
        q.cache_info["stack_dtype"] == "int8",
        f"stack_dtype telemetry {q.cache_info['stack_dtype']!r}",
    )
    # int8 transports agree bitwise (quantized once, per partition)
    q_mat = trainer.train(
        dataclasses.replace(base, stack_dtype="int8"), data
    )
    check(bitwise(q, q_mat), "int8 ring+pipelined != int8 materialized")
    qp = np.concatenate(
        [np.asarray(l).ravel() for l in jax.tree.leaves(q.final_params)]
    )
    check(np.isfinite(qp).all(), "int8 run produced non-finite params")

    # ---- dispatch counts: one cohort dispatch for the int8 ring sweep --
    d0 = REGISTRY.counter("cohort.dispatches").value
    cfgs = [
        dataclasses.replace(
            base, scheme=sch, stack_mode="ring", ring_pipeline="on",
            stack_dtype="int8", seed=sd,
        )
        # repcoded and approx share the FRC assignment -> one ring cohort
        for sch in ("repcoded", "approx")
        for sd in (0, 1)
    ]
    from erasurehead_tpu import schemes as schemes_lib

    cfgs = [
        dataclasses.replace(c, num_collect=6)
        if schemes_lib.get(c.scheme).needs_num_collect else c
        for c in cfgs
    ]
    cohort = trainer.train_cohort(cfgs, data)
    check(
        REGISTRY.counter("cohort.dispatches").value - d0 == 1,
        "int8 ring cohort did not run as ONE dispatch",
    )
    check(
        cohort[0].cache_info["cohort_size"] == len(cfgs),
        f"cohort size {cohort[0].cache_info['cohort_size']} != {len(cfgs)}",
    )

    # ---- cache hygiene: reruns are pure hits; pins alive post-donation --
    stats0 = cache.stats().snapshot()
    for cfg in (
        base,
        dataclasses.replace(base, stack_mode="ring", ring_pipeline="on"),
        dataclasses.replace(
            base, stack_mode="ring", ring_pipeline="on", stack_dtype="int8"
        ),
    ):
        rerun = trainer.train(cfg, data)
        check(
            rerun.cache_info["data_hit"] and rerun.cache_info["exec_hits"],
            f"rerun of {cfg.stack_mode}/{cfg.stack_dtype} missed the caches",
        )
    stats1 = cache.stats().snapshot()
    check(
        stats1["exec_misses"] == stats0["exec_misses"],
        "reruns recompiled (donation or keys broke executable reuse)",
    )
    for d, _nbytes in cache._data_cache.values():
        for leaf in jax.tree.leaves((d.Xp, d.yp, d.Xw, d.yw)):
            if isinstance(leaf, jax.Array) and leaf.is_deleted():
                check(False, "a cached device array was donated (deleted)")

    print(
        f"roofline-smoke: f32 pins ok; stack bytes materialized="
        f"{m.cache_info['stack_bytes']} ring={r.cache_info['stack_bytes']} "
        f"int8_ring={q.cache_info['stack_bytes']}; "
        f"{len(cfgs)}-trajectory int8 ring cohort = 1 dispatch; "
        f"reruns all cache hits"
    )
    for f in failures:
        print(f"FAIL: {f}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
