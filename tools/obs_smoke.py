"""obs-smoke: CPU end-to-end drive of the live telemetry plane.

`make obs-smoke` asserts, end to end:

  1. a sync + a pipelined training run each emit a typed
     `critical_path` record whose sim ledger sums to the simulated
     clock and whose host ledger sums to the measured wall (the event
     validator re-checks both within obs/events.CRITICAL_PATH_TOL);
  2. the streaming reducer tails the SAME events.jsonl the runs wrote
     and reproduces the round count in its windowed series, then the
     `erasurehead-tpu top` renderer draws one frame from that file;
  3. the online regime estimator flags an exp(0.05) -> exp(2.0)
     arrival-rate shift within its detect_rounds budget and the
     emitted `regime` events validate;
  4. the Prometheus exporter renders the live registry + reducer
     gauges as valid text exposition (every sample line parses,
     deterministic across a double render);
  5. the telemetry plane stays observation-only: the instrumented run
     (capture + attached reducer) and the dark run produce bitwise-
     identical parameter trajectories.
"""

import json
import os
import re
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from erasurehead_tpu.data.synthetic import generate_gmm  # noqa: E402
from erasurehead_tpu.obs import events as obs_events  # noqa: E402
from erasurehead_tpu.obs import exporter as exporter_lib  # noqa: E402
from erasurehead_tpu.obs import regime as regime_lib  # noqa: E402
from erasurehead_tpu.obs.metrics import REGISTRY  # noqa: E402
from erasurehead_tpu.obs.timeseries import (  # noqa: E402
    TimeseriesReducer,
    tail_path,
)
from erasurehead_tpu.train import cache, trainer  # noqa: E402
from erasurehead_tpu.utils.config import RunConfig  # noqa: E402

W, ROUNDS = 6, 5
OUT = "/tmp/eh-obs-smoke"

_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? (-?\d[\d.e+-]*|NaN)$"
)


def main() -> int:
    import jax

    os.makedirs(OUT, exist_ok=True)
    ds = generate_gmm(240, 12, W, seed=0)

    def cfg(scheme, **kw):
        base = dict(
            scheme=scheme, n_workers=W, n_stragglers=1, rounds=ROUNDS,
            n_rows=240, n_cols=12, lr_schedule=1.0, add_delay=True,
            compute_mode="deduped", seed=0,
        )
        base.update(kw)
        return RunConfig(**base)

    # 1) critical-path attribution across trainer flavors, ledgers close
    events_path = os.path.join(OUT, "events.jsonl")
    cache.clear()
    red = TimeseriesReducer()
    handle = red.attach()
    try:
        with obs_events.capture(events_path):
            sync_res = trainer.train(cfg("cyccoded"), ds)
            trainer.train(
                cfg("avoidstragg", pipeline_depth=1, update_rule="GD"), ds
            )
    finally:
        handle.detach()
    errors = obs_events.validate_file(events_path)
    assert not errors, "event log invalid:\n" + "\n".join(errors)
    with open(events_path) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    cps = [r for r in recs if r["type"] == "critical_path"]
    assert len(cps) == 2, f"expected 2 critical_path records, got {len(cps)}"
    for cp in cps:
        sim = sum(cp["sim_components"].values())
        host = sum(cp["components"].values())
        assert abs(sim - cp["sim_total_s"]) <= 0.05 * max(
            cp["sim_total_s"], 1e-9
        )
        assert abs(host - cp["wall_s"]) <= 0.05 * max(cp["wall_s"], 1e-9)
    print(
        "obs-smoke: 2 critical_path records validate; "
        f"sync straggler-wait share "
        f"{cps[0]['fractions']['straggler_wait']:.2f}, pipelined "
        f"overlap hidden {cps[1]['overlap_hidden_s']:.3f}s"
    )

    # 2) the reducer (attached live above, and tailing the file now)
    # agrees with the runs it watched; `top` renders a frame from it
    snap = red.snapshot()
    live_rounds = sum(w["rounds"] for w in snap["windows"])
    assert live_rounds == 2 * ROUNDS, (live_rounds, 2 * ROUNDS)
    tailed = tail_path(events_path).snapshot()
    assert sum(w["rounds"] for w in tailed["windows"]) == 2 * ROUNDS
    assert tailed["malformed"] == 0
    rc = exporter_lib.top_main([events_path])
    assert rc == 0, f"top renderer failed: rc={rc}"
    print(
        f"obs-smoke: reducer saw {live_rounds} rounds live and tailed; "
        "top rendered one frame"
    )

    # 3) regime estimator detects a rate shift within its round budget
    regime_path = os.path.join(OUT, "regime.jsonl")
    rng = np.random.default_rng(0)
    with obs_events.capture(regime_path):
        est = regime_lib.ArrivalRegimeEstimator(detect_rounds=4)
        for r in range(20):
            e = est.update(r, rng.exponential(0.05, W))
            assert not e.shifted, f"false positive at round {r}"
        detected = None
        for r in range(20, 30):
            if est.update(r, rng.exponential(2.0, W)).shifted:
                detected = r
                break
    assert detected is not None and detected < 24, detected
    errors = obs_events.validate_file(regime_path)
    assert not errors, "regime log invalid:\n" + "\n".join(errors)
    print(
        f"obs-smoke: regime shift at round 20 detected at round "
        f"{detected} (budget 24)"
    )

    # 4) Prometheus exposition hygiene over the LIVE registry + gauges
    gauges = red.gauges()
    text = exporter_lib.render_prometheus(REGISTRY, gauges)
    assert text == exporter_lib.render_prometheus(REGISTRY, gauges)
    for line in text.splitlines():
        if line and not line.startswith("#"):
            assert _SAMPLE.match(line), f"bad exposition line: {line}"
    n_samples = sum(
        1 for line in text.splitlines()
        if line and not line.startswith("#")
    )
    assert "erasurehead_rounds_per_wall_sec" in text
    print(f"obs-smoke: /metrics exposition valid ({n_samples} samples)")

    # 5) observation-only: dark rerun is bitwise-identical
    cache.clear()
    dark = trainer.train(cfg("cyccoded"), ds)
    for a, b in zip(
        jax.tree.leaves(sync_res.params_history),
        jax.tree.leaves(dark.params_history),
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            "telemetry plane perturbed the trajectory"
        )
    print("obs-smoke: instrumented vs dark trajectories bitwise OK")
    print(f"obs-smoke: OK (events -> {events_path})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
