#!/usr/bin/env bash
# The full on-TPU measurement program, one command — run when the relay is
# healthy. Appends every JSON result line to tools/measurements.jsonl with
# a tag, so a flaky relay costs only the remaining entries on rerun.
#
#   bash tools/tpu_measurements.sh [out.jsonl]
#
# Covers: canonical dense bench (f32 + bfloat16 data), the pallas kernel
# race, the dense-lowering profile (precision/bf16/pass split), the sparse
# canonical shapes (covtype + amazon) across faithful/deduped x
# scalar/lanes lowerings, and the sparse rmatvec profile.
set -u -o pipefail
cd "$(dirname "$0")/.."
OUT="${1:-tools/measurements.jsonl}"
export PYTHONPATH="${PYTHONPATH:-}:$(pwd)"

run() { # run <tag> <timeout_s> <cmd...> — per-entry timeout so a relay
        # wedge mid-program costs one entry, not the rest of the sweep;
        # stderr goes to a per-tag log so failures keep their diagnostics.
        # Already-captured tags are skipped, so a rerun after a mid-sweep
        # wedge resumes at the first missing entry (RERUN_ALL=1 overrides).
  local tag="$1" tmo="$2"; shift 2
  if [ -z "${RERUN_ALL:-}" ] && [ -f "$OUT" ] \
     && grep -q "\"tag\": \"$tag\"" "$OUT"; then
    echo "=== $tag: already captured, skipping (RERUN_ALL=1 to redo)" >&2
    return
  fi
  echo "=== $tag ($tmo s): $*" >&2
  local line rc
  line="$(timeout "$tmo" "$@" 2>"$OUT.$tag.log" | tail -1)"
  rc=$?
  # Record ONLY exit-0 runs whose last line is valid JSON from a real TPU:
  # garbage would corrupt the decision record, and — because the resume
  # check greps for the tag — any recorded line marks the entry captured
  # forever. In particular bench.py exits 0 with a platform:"cpu" fallback
  # line when the relay wedges mid-sweep; that must stay un-captured so
  # the next healthy window retries it. A failure appends nothing.
  if [ "$rc" -eq 0 ] && [ -n "$line" ] \
     && printf '%s' "$line" | python -c '
import json, sys
d = json.load(sys.stdin)
sys.exit(1 if d.get("platform") in ("cpu", "none") else 0)' 2>/dev/null; then
    printf '{"tag": "%s", "result": %s}\n' "$tag" "$line" >> "$OUT"
    echo "$tag -> $line" >&2
  else
    echo "$tag -> FAILED rc=$rc (see $OUT.$tag.log)" >&2
  fi
}

# Ordered by value-per-wedge-risk: the round-2 window died at the covtype
# faithful+lanes8 entry ("TPU device error" wedging every later process),
# so the entries that decide round-3 items run FIRST and the known-risky
# lane benches run LAST.

# dense_profile_v2: the margin-lowering variants (matmul2d / cols8 /
# default-prec / raw-stream probes) added after the r2 dense_profile capture
run dense_profile_v2 900 python tools/profile_dense.py
# one targeted fusion-favorable retry (VERDICT r2 #8): tall rows, F=64,
# bf16-stored stack — the kernel streams half the bytes in one pass
run kernel_race_bf16_tallR 900 python tools/kernel_race.py \
    --slots 30 --rows 26400 --cols 64 --dtype bfloat16
run sparse_profile 900  python tools/profile_sparse.py
# full production path under the margin_cols lowering — decides the
# production default against the captured dense_f32 entry
run dense_f32_margincols8 1800 env BENCH_MARGIN_COLS=8 python bench.py

# the flagship sparse shapes: FieldOnehot pair tables (halves the lookup
# count; amazon's 5.5k-category fields exceed the pair cap and fall back
# to singles, which still drops the value payload), then the plain benches
for shape in amazon covtype; do
  run "sparse_${shape}_faithful_fields"  900 python tools/bench_sparse.py --shape "$shape" --format fields
  run "sparse_${shape}_deduped_fields"   900 python tools/bench_sparse.py --shape "$shape" --mode deduped --format fields
  run "sparse_${shape}_faithful"         900 python tools/bench_sparse.py --shape "$shape"
  run "sparse_${shape}_deduped"          900 python tools/bench_sparse.py --shape "$shape" --mode deduped
done

# bench.py manages wedge-probing internally — give it its full budget
run dense_f32      1800 python bench.py
run dense_bf16     1800 env BENCH_DTYPE=bfloat16 python bench.py
run kernel_race    900  python tools/kernel_race.py

# lane-replicated gather benches last: the [rows, nnz, L] gather temps are
# the largest allocations in the program (the r2 wedge followed a lane-
# temp OOM); a wedge here costs nothing already captured
for shape in amazon covtype; do
  run "sparse_${shape}_faithful_lanes8"  900 python tools/bench_sparse.py --shape "$shape" --lanes 8
  run "sparse_${shape}_deduped_lanes8"   900 python tools/bench_sparse.py --shape "$shape" --mode deduped --lanes 8
  run "sparse_${shape}_deduped_lanes128" 900 python tools/bench_sparse.py --shape "$shape" --mode deduped --lanes 128
done

echo "measurements appended to $OUT" >&2
