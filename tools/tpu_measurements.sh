#!/usr/bin/env bash
# The full on-TPU measurement program, one command — run when the relay is
# healthy. Appends every JSON result line to tools/measurements.jsonl with
# a tag, so a flaky relay costs only the remaining entries on rerun.
#
#   bash tools/tpu_measurements.sh [out.jsonl]
#
# Covers: canonical dense bench (f32 + bfloat16 data), the pallas kernel
# race, the dense-lowering profile (precision/bf16/pass split + margin
# lowerings), the sparse canonical shapes (covtype + amazon) across
# faithful/deduped x scalar/lanes/fields lowerings, and the sparse
# gather/scatter candidate profile.
set -u -o pipefail
cd "$(dirname "$0")/.."
OUT="${1:-tools/measurements.jsonl}"
export PYTHONPATH="${PYTHONPATH:-}:$(pwd)"

. "$(dirname "$0")/measure_lib.sh"

# Ordered by value-per-wedge-risk, revised after the round-3 window-1
# post-mortem: the 900 s per-entry budget is mostly COMPILE time over the
# relay, so the multi-variant profiles are split into small tagged groups
# (profile tools take --only) that each fit the budget; covtype (known-
# compilable shapes) runs before amazon; and the amazon fields entries —
# the window-1 run died mid-compile on sparse_amazon_faithful_fields —
# run dead last.

# dense_profile_v2, split: (a) the margin-lowering variants that decide
# VERDICT r3 item 2, (b) the raw-stream/bf16 attribution probes
run dense_profile_margins 1200 python tools/profile_dense.py \
    --only margin_matmul2d,margin_cols8,margin_default_prec,margin_only
run dense_profile_streams 1200 python tools/profile_dense.py \
    --only two_pass,bf16_data,raw_stream
# one targeted fusion-favorable retry (VERDICT r2 #8): tall rows, F=64,
# bf16-stored stack — the kernel streams half the bytes in one pass.
# Window-1 measured the logistic half (pallas 3.48 vs XLA 1.87 ms, loses)
# before timing out; 1800 s covers both halves' compiles.
run kernel_race_bf16_tallR 1800 python tools/kernel_race.py \
    --slots 30 --rows 26400 --cols 64 --dtype bfloat16
# sparse_profile, split (window-1 measured 8 of 14 candidates in 900 s
# before the wedge — their numbers live only in the window-1 .log, so the
# groups below re-capture ALL candidates into the resumable record):
# pairs/packed first (the undecided ones), then base, then the measured-
# loser re-captures last
run sparse_profile_pairs  1200 python tools/profile_sparse.py \
    --only margin_pairs,scatter_pairs
run sparse_profile_packed 1200 python tools/profile_sparse.py \
    --only margin_packed8,scatter_packed8
run sparse_profile_base   1200 python tools/profile_sparse.py \
    --only margin_gather,scatter_ms,margin_rowgather8,scatter_rows8
# full production path under the margin_cols lowering — decides the
# production default against the captured dense_f32 entry
run dense_f32_margincols8 1800 env BENCH_MARGIN_COLS=8 python bench.py

# flagship sparse shapes, covtype (known-good compiles) before amazon;
# fields = FieldOnehot pair tables (halves the lookup count where pairs
# fit the cap — covtype; amazon falls back to singles). Fields entries pin
# --flat off: flat_grad="auto" now resolves FieldOnehot to the flat
# lowering (step.resolve_flat_grad), so these stay the PER-SLOT baselines
# — the flat races live in tpu_measurements_flat.sh. The plain covtype
# entries are r2-captured and resume-skipped, but stay in the program so
# RERUN_ALL=1 refreshes the full faithful/deduped x covtype/amazon grid.
run sparse_covtype_faithful_fields  1200 python tools/bench_sparse.py --shape covtype --format fields --flat off
# (timed out its 1200 s budget in r3 window 2, but the relay wedge began
# mid-entry so that run proves nothing; one bounded retry as baseline)
run sparse_covtype_deduped_fields   600 python tools/bench_sparse.py --shape covtype --mode deduped --format fields --flat off
run sparse_covtype_faithful         1200 python tools/bench_sparse.py --shape covtype
run sparse_covtype_deduped          1200 python tools/bench_sparse.py --shape covtype --mode deduped
run sparse_amazon_faithful          1200 python tools/bench_sparse.py --shape amazon
run sparse_amazon_deduped           1200 python tools/bench_sparse.py --shape amazon --mode deduped

# bench.py manages wedge-probing internally — give it its full budget
run dense_f32      1800 python bench.py
run dense_bf16     1800 env BENCH_DTYPE=bfloat16 python bench.py
# ring-streamed faithful stack (stack_mode=ring): bitwise-identical
# science at 1/(s+1) the device data — races the materialized canonical
# for the step-time cost of the per-round ppermute hops, and captures the
# memory_analysis/stack_bytes telemetry on real silicon
run dense_f32_ring  1800 env BENCH_STACK=ring python bench.py
run dense_bf16_ring 1800 env BENCH_STACK=ring BENCH_DTYPE=bfloat16 python bench.py
# PR-6 memory-system levers (ISSUE 6): double-buffered ring transport
# (bitwise-identical; decides RING_PIPELINE_DEFAULT), the int8 compressed
# stack (4x fewer streamed bytes; fidelity extra rides in the payload),
# and the donation before-row (the canonical run now donates by default)
run dense_f32_ringpipe   1800 env BENCH_STACK=ring BENCH_RING_PIPELINE=on python bench.py
run dense_int8_ring      1800 env BENCH_STACK=ring BENCH_STACK_DTYPE=int8 python bench.py
run dense_int8_ringpipe  1800 env BENCH_STACK=ring BENCH_RING_PIPELINE=on BENCH_STACK_DTYPE=int8 python bench.py
run dense_int8           1800 env BENCH_STACK_DTYPE=int8 python bench.py
run dense_f32_nodonate   1800 env BENCH_DONATE=off python bench.py
# composed out-of-core streaming (ISSUE 17): the canonical run over
# windowed partition stacks behind the prefetch pipeline, composed with
# ring transport (window 6 of 30 resident; the approx layout is window-
# uniform at 6). The payload's outofcore_composed extra carries the
# streamed-vs-resident overhead, overlap efficiency, staged-window
# device bytes, and the windowed-cohort-vs-sequential trajectory rate
# (cohort_stream re-captures it on the int8 stack so both claims land
# even if one entry dies mid-window).
run dense_f32_streamring  1800 env BENCH_STACK=ring BENCH_RESIDENCY=streamed BENCH_STREAM_WINDOW=6 python bench.py
run dense_int8_streamring 1800 env BENCH_STACK=ring BENCH_STACK_DTYPE=int8 BENCH_RESIDENCY=streamed BENCH_STREAM_WINDOW=6 python bench.py
run cohort_stream         1800 env BENCH_STACK=ring BENCH_STACK_DTYPE=int8 BENCH_RESIDENCY=streamed BENCH_STREAM_WINDOW=6 BENCH_OUTOFCORE_COHORT=16 python bench.py
# deduped compute mode on the dense flagship: bit-compatible gradients at
# 1/(s+1) the HBM traffic — the framework's structural win over the
# faithful reference protocol, never yet TPU-measured for dense
run dense_f32_deduped  1800 env BENCH_MODE=deduped python bench.py
run dense_bf16_deduped 1800 env BENCH_MODE=deduped BENCH_DTYPE=bfloat16 python bench.py

# fused blockwise decode + the measured autotuning plane (ISSUE 19): race
# the fused per-leaf decode against treewise pack-then-einsum AND the
# pallas GLM kernel against XLA's lowering on real silicon at a deepmlp
# blockwise shape; verdicts persist to the repo-local decision cache so a
# later run with --block-decode auto / --use-pallas auto lowers under the
# measured winner, not the CPU-era constant
run fused_decode 1800 env ERASUREHEAD_TUNE_CACHE=tools/tune_decisions.json \
    python -m erasurehead_tpu.cli tune --json \
    --race block_decode --race glm_fused \
    --model deepmlp --workers 8 --rows 4096 --cols 256 --rounds 8
run kernel_race    900  python tools/kernel_race.py

# lane-replicated gather benches: the [rows, nnz, L] gather temps are
# the largest allocations in the program (the r2 wedge followed a lane-
# temp OOM)
for shape in covtype amazon; do
  run "sparse_${shape}_faithful_lanes8"  900 python tools/bench_sparse.py --shape "$shape" --lanes 8
  run "sparse_${shape}_deduped_lanes8"   900 python tools/bench_sparse.py --shape "$shape" --mode deduped --lanes 8
  run "sparse_${shape}_deduped_lanes128" 900 python tools/bench_sparse.py --shape "$shape" --mode deduped --lanes 128
done

# window-1 measured losers, re-captured into the resumable record (their
# window-1 numbers exist only in a .log): the sort/presorted segment-sum
# candidates, the 128-wide lane variants, and packed128
run sparse_profile_rest 1200 python tools/profile_sparse.py \
    --only sort_in_jit,presorted,margin_rowgather128,scatter_rows128
run sparse_profile_packed128 1200 python tools/profile_sparse.py \
    --only margin_packed128,scatter_packed128

# round-4 additions (VERDICT r3 #3 and #5), cheap compiles:
# measured-arrival AGC on real silicon — worker_timeset as a device
# measurement, plus the AGC/naive protocol-rate ratio under real
# (induced) heterogeneity; writes artifacts/measured_arrival_tpu.json
run measured_arrival_agc 900 python tools/bench_measured.py
# independent bandwidth-ceiling cross-check: out-of-scan stream probes +
# an xplane device trace of the production-shaped two-pass step —
# hardens (or reopens) the 126 GB/s in-scan floor claim (BASELINE.md)
run dense_hbm_crosscheck 900 python tools/profile_hbm.py

# the fully on-device control plane at canonical scale (VERDICT r4 #9):
# 10k rounds of W=30 cyclic-MDS with table decode in ONE jitted scan —
# the reference's 10k per-iteration host lstsq loop as a single dispatch
run dynamic_mds_w30_10k 1500 python tools/bench_dynamic.py

# amazon fields LAST: round-3 window 1 died mid-compile here (relay
# terminal down at 01:52Z with this entry in flight; the compile itself
# is proven cheap — 8 s on forced-CPU — so this is pure wedge paranoia).
# K=44 singles fallback.
run sparse_amazon_faithful_fields  1200 python tools/bench_sparse.py --shape amazon --format fields --flat off
run sparse_amazon_deduped_fields   1200 python tools/bench_sparse.py --shape amazon --mode deduped --format fields --flat off

echo "measurements appended to $OUT" >&2
