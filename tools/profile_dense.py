"""Micro-profile the dense GLM gradient's lowering variants at the bench
shape on TPU, inside one dispatch. The bench measured ~215 GB/s (26% of
v5e HBM peak) for the two-pass gradient; this attributes where the other
74% goes and what buys it back:

  two_pass_highest — the production lowering (margin + transpose einsums,
                     precision=HIGHEST; science-exact)
  two_pass_default — same with default (bf16-rounded MXU) precision: an
                     upper bound showing what precision costs (science-
                     INVALID for convex-GLM curves, measurement only)
  bf16_data        — bf16 X/y with bf16-cast vector operands and f32 MXU
                     accumulation — the production cfg.dtype=bfloat16
                     lowering (ops/features.py): halves HBM traffic
  margin_only      — one pass, to split the two passes' costs

Usage: python tools/profile_dense.py [--slots 90] [--rows 4400] [--cols 128]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from _relay import with_retries


def time_scanned(fn, beta0, iters=50, reps=5):
    @jax.jit
    def many(b0):
        def body(b, _):
            g = fn(b)
            return g / (jnp.linalg.norm(g) + 1.0), None

        bN, _ = lax.scan(body, b0, None, length=iters)
        return bN

    with_retries(lambda: jax.block_until_ready(many(beta0)))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(many(beta0))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts)) / iters


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", type=int, default=90)
    ap.add_argument("--rows", type=int, default=4400)
    ap.add_argument("--cols", type=int, default=128)
    ap.add_argument(
        "--only", default="",
        help="comma-separated substrings: measure only matching variants "
             "(each costs a slow relay compile; the sweep runs this profile "
             "as small tagged groups that fit a per-entry timeout)",
    )
    args = ap.parse_args()
    M, R, F = args.slots, args.rows, args.cols

    platform = jax.devices()[0].platform
    print(f"dense profile: {platform} M={M} R={R} F={F}", file=sys.stderr)

    key = jax.random.PRNGKey(0)
    kx, ky, kw, kb = jax.random.split(key, 4)
    X = jax.random.normal(kx, (M, R, F), jnp.float32)
    y = jnp.sign(jax.random.normal(ky, (M, R), jnp.float32))
    w = jax.random.uniform(kw, (M,), jnp.float32)
    beta0 = jax.random.normal(kb, (F,), jnp.float32)
    Xb, yb = X.astype(jnp.bfloat16), y.astype(jnp.bfloat16)

    def grad(Xa, ya, prec):
        def f(beta):
            # cast the tiny vector operand to the DATA dtype so the big
            # stack streams as stored (the production features.py rule —
            # promoting Xa would let XLA hoist an f32 copy out of the scan)
            p = jnp.einsum(
                "mrf,f->mr", Xa, beta.astype(Xa.dtype),
                precision=prec, preferred_element_type=jnp.float32,
            )
            yf = ya.astype(jnp.float32)
            s = (-yf / (jnp.exp(p * yf) + 1.0)) * w[:, None]
            return jnp.einsum(
                "mrf,mr->f", Xa, s.astype(Xa.dtype),
                precision=prec, preferred_element_type=jnp.float32,
            )

        return f

    HI, DEF = lax.Precision.HIGHEST, lax.Precision.DEFAULT
    results = {"platform": platform, "shape": [M, R, F]}

    cases = {
        "two_pass_highest": (grad(X, y, HI), 2 * X.nbytes),
        "two_pass_default": (grad(X, y, DEF), 2 * X.nbytes),
        "bf16_data": (grad(Xb, yb, DEF), 2 * Xb.nbytes),
    }

    # the production flat-stack lowering (parallel/step.make_flat_grad_fn):
    # slot axes flattened so the margin is one [M*R, F] matmul (measured at
    # the raw-stream floor) and the weights fold into the residual
    def grad_flat(Xa, ya, prec):
        X2 = Xa.reshape(M * R, F)
        y2 = ya.reshape(M * R)
        w2 = jnp.broadcast_to(w[:, None], (M, R)).reshape(M * R)

        def f(beta):
            p = jnp.matmul(
                X2, beta.astype(Xa.dtype),
                precision=prec, preferred_element_type=jnp.float32,
            )
            yf = y2.astype(jnp.float32)
            s = (-yf / (jnp.exp(p * yf) + 1.0)) * w2
            return jnp.matmul(
                X2.T, s.astype(Xa.dtype),
                precision=prec, preferred_element_type=jnp.float32,
            )

        return f

    # names deliberately avoid the substrings two_pass/bf16_data so the
    # main sweep's --only filters (tpu_measurements.sh) never pick these up
    cases["flatstack_full"] = (grad_flat(X, y, HI), 2 * X.nbytes)
    cases["flatstack_bf16"] = (grad_flat(Xb, yb, DEF), 2 * Xb.nbytes)

    def margin_only(beta):
        p = jnp.einsum("mrf,f->mr", X, beta, precision=HI)
        # a nonlinear consumer: sum(X@b) alone is reassociable to
        # (sum X)@b, which XLA would hoist out of the scan entirely
        return beta * 0.999 + jnp.sum(jnp.tanh(p)) / F

    cases["margin_only"] = (margin_only, X.nbytes)

    # --- what is the chip's actual achievable stream rate in-scan? A pure
    # elementwise read+reduce of the stack, no contraction structure at all:
    # the honest denominator for "percent of roofline" claims. The beta-
    # dependent multiply keeps the reduction loop-variant (unhoistable).
    def raw_stream(beta):
        return beta * 0.999 + jnp.sum(X * beta[0]) / F

    cases["raw_stream"] = (raw_stream, X.nbytes)

    def raw_stream_bf16(beta):
        return beta * 0.999 + jnp.sum(Xb * beta[0].astype(jnp.bfloat16)) / F

    cases["raw_stream_bf16"] = (raw_stream_bf16, Xb.nbytes)

    # --- margin lowering variants: is the mrf,f->mr contraction (reduce
    # over the minor/lane dim) what keeps the stream at ~120 GB/s, and does
    # a different shape for the same math fix it?
    X2 = X.reshape(M * R, F)

    def margin_matmul2d(beta):
        p = jnp.matmul(X2, beta, precision=HI)
        return beta * 0.999 + jnp.sum(jnp.tanh(p)) / F

    cases["margin_matmul2d"] = (margin_matmul2d, X.nbytes)

    def margin_cols8(beta):
        # replicate beta to [F, 8] so the product is a real matmul with an
        # (8,128)-tileable output; column 0 is the answer. Trades an 8x
        # output write (tiny vs X) for MXU-shaped lowering.
        bt = lax.optimization_barrier(jnp.broadcast_to(beta[:, None], (F, 8)))
        p = jnp.matmul(X2, bt, precision=HI)
        return beta * 0.999 + jnp.sum(jnp.tanh(p[:, 0])) / F

    cases["margin_cols8"] = (margin_cols8, X.nbytes)

    def margin_dot_bf16ops(beta):
        # stream f32 X but contract with DEFAULT (bf16-pass) precision —
        # isolates whether the HIGHEST 6-pass MXU recombination is the cost
        p = jnp.matmul(X2, beta, precision=DEF)
        return beta * 0.999 + jnp.sum(jnp.tanh(p)) / F

    cases["margin_default_prec"] = (margin_dot_bf16ops, X.nbytes)

    only = [s for s in args.only.split(",") if s]
    for name, (fn, traffic) in cases.items():
        if only and not any(s in name for s in only):
            continue
        ms = time_scanned(fn, beta0) * 1e3
        gbps = traffic / (ms / 1e3) / 1e9
        results[f"{name}_ms"] = round(ms, 4)
        results[f"{name}_gbps"] = round(gbps, 1)
        print(f"dense profile: {name} {ms:.3f}ms {gbps:.0f}GB/s",
              file=sys.stderr)

    print(json.dumps(results))


if __name__ == "__main__":
    main()
