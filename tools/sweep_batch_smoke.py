#!/usr/bin/env python
"""Smoke-check the trajectory-batched sweep engine on CPU
(`make sweep-batch-smoke`).

Runs a 7-scheme x 2-seed deduped compare() with --batch-trajectories auto
under a telemetry capture, then asserts the dispatch-amortization contract
via the obs/metrics counters:

  - cohort.dispatches <= the number of cohorts plan_cohorts planned
    (the whole deduped sweep must collapse, not run per-config);
  - cohort.trajectories == the number of configs;
  - the sweep caches performed exactly one scan compile and one data
    upload for the whole cohort;
  - the events.jsonl (cohort record included) passes the schema check.

Exit 0 = all assertions hold; 1 = failure (printed).
"""

import os
import sys
import tempfile

# runnable from anywhere without an install (the tools/ convention)
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU relay


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.obs import events as events_lib
    from erasurehead_tpu.obs.metrics import REGISTRY
    from erasurehead_tpu.train import cache, experiments
    from erasurehead_tpu.utils.config import RunConfig

    W, rounds, seeds = 8, 4, (0, 1)
    data = generate_gmm(W * 16, 24, n_partitions=W, seed=0)
    common = dict(
        n_workers=W, n_stragglers=1, rounds=rounds, n_rows=W * 16,
        n_cols=24, update_rule="AGD", lr_schedule=0.5, add_delay=True,
        compute_mode="deduped",
    )
    schemes = [
        ("naive", {}),
        ("cyccoded", {}),
        ("repcoded", {}),
        ("approx", {"num_collect": 6}),
        ("avoidstragg", {}),
        ("randreg", {"num_collect": 6}),
        ("deadline", {"deadline": 1.0}),
    ]
    configs = {
        f"{s}_seed{sd}": RunConfig(
            **{**common, **extra, "scheme": s, "seed": sd}
        )
        for s, extra in schemes
        for sd in seeds
    }
    n_cohorts = sum(1 for _, b in experiments.plan_cohorts(configs) if b)

    cache.clear()
    for name in ("cohort.dispatches", "cohort.trajectories",
                 "cohort.sequential_runs"):
        REGISTRY.counter(name).reset()
    events_path = os.path.join(
        tempfile.mkdtemp(prefix="eh-sweep-batch-smoke-"), "events.jsonl"
    )
    with events_lib.capture(events_path):
        rows = experiments.compare(configs, data, batch="auto")

    dispatches = REGISTRY.counter("cohort.dispatches").value
    trajectories = REGISTRY.counter("cohort.trajectories").value
    stats = cache.stats()
    failures = []
    if len(rows) != len(configs):
        failures.append(f"expected {len(configs)} rows, got {len(rows)}")
    if dispatches > n_cohorts:
        failures.append(
            f"cohort.dispatches={dispatches} exceeds the {n_cohorts} "
            "planned cohort(s): the sweep did not batch"
        )
    if trajectories != len(configs):
        failures.append(
            f"cohort.trajectories={trajectories} != {len(configs)} configs"
        )
    if stats.exec_misses > n_cohorts:
        failures.append(
            f"{stats.exec_misses} scan compiles for {n_cohorts} cohort(s)"
        )
    if stats.data_misses > n_cohorts:
        failures.append(
            f"{stats.data_misses} data uploads for {n_cohorts} cohort(s)"
        )
    schema_errors = events_lib.validate_file(events_path)
    failures.extend(f"events schema: {e}" for e in schema_errors)
    if not any(
        r.cache and r.cache.get("cohort_dispatches") for r in rows
    ):
        failures.append("no row carries cohort cache telemetry")

    print(
        f"sweep-batch-smoke: {len(configs)} trajectories "
        f"({len(schemes)} schemes x {len(seeds)} seeds) -> "
        f"{dispatches} dispatch(es) of {n_cohorts} planned cohort(s); "
        f"compiles={stats.exec_misses} uploads={stats.data_misses}"
    )
    print(f"events -> {events_path}")
    for f in failures:
        print(f"FAIL: {f}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
