#!/usr/bin/env bash
# CPU rehearsal of every still-queued tpu_measurements.sh entry, in light
# form: validates the exact tool/flag surface each sweep command will use
# so a healthy relay window is pure harvest, never debugging. Timings are
# meaningless here — the point is that every command parses, runs, and
# emits its JSON line. Writes tools/rehearsal.jsonl (committed as the
# readiness record).
#
#   bash tools/sweep_rehearsal.sh [out.jsonl]
set -u -o pipefail  # rc must be the rehearsed command's, not tail's
cd "$(dirname "$0")/.."
OUT="${1:-tools/rehearsal.jsonl}"
: > "$OUT"
export PYTHONPATH="${PYTHONPATH:-}:$(pwd)"
# scrub the axon tunnel (memory: a CPU process dialing the relay can wedge
# a concurrent TPU job) and pin the virtual multi-device CPU platform
SCRUB=(env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu
       XLA_FLAGS=--xla_force_host_platform_device_count=8)

run() { # run <sweep_tag> <timeout_s> <cmd...>
  local tag="$1" tmo="$2"; shift 2
  echo "=== rehearse $tag: $*" >&2
  local line rc
  line="$(timeout "$tmo" "${SCRUB[@]}" "$@" 2>"$OUT.$tag.log" | tail -1)"
  rc=$?
  # OK requires all three: command exited 0, produced a line, and the line
  # is valid JSON — a crash that printed diagnostics to stdout must be
  # recorded as the failure it is, not embedded in the readiness record
  if [ "$rc" -eq 0 ] && [ -n "$line" ] \
     && printf '%s' "$line" | python -m json.tool >/dev/null 2>&1; then
    printf '{"tag": "%s", "result": %s}\n' "$tag" "$line" >> "$OUT"
    echo "$tag OK" >&2
  else
    printf '{"tag": "%s", "result": {"error": "rehearsal failed, rc=%s"}}\n' \
      "$tag" "$rc" >> "$OUT"
    echo "$tag FAILED rc=$rc (see $OUT.$tag.log)" >&2
  fi
}

run dense_profile_v2 600 python tools/profile_dense.py \
    --slots 4 --rows 256 --cols 64
run kernel_race_bf16_tallR 600 python tools/kernel_race.py \
    --slots 2 --rows 128 --cols 64 --iters 2 --dtype bfloat16 --interpret
run sparse_profile 600 python tools/profile_sparse.py \
    --slots 4 --rows 256 --nnz 4 --cols 512
run dense_f32_margincols8 600 env BENCH_MARGIN_COLS=8 python bench.py

for shape in amazon covtype; do
  run "sparse_${shape}_faithful_fields"  600 python tools/bench_sparse.py --shape "$shape" --format fields --flat off --light
  run "sparse_${shape}_deduped_fields"   600 python tools/bench_sparse.py --shape "$shape" --mode deduped --format fields --flat off --light
  run "sparse_${shape}_faithful"         600 python tools/bench_sparse.py --shape "$shape" --light
  run "sparse_${shape}_deduped"          600 python tools/bench_sparse.py --shape "$shape" --mode deduped --light
  run "sparse_${shape}_faithful_lanes8"  600 python tools/bench_sparse.py --shape "$shape" --lanes 8 --light
  run "sparse_${shape}_deduped_lanes8"   600 python tools/bench_sparse.py --shape "$shape" --mode deduped --lanes 8 --light
  run "sparse_${shape}_deduped_lanes128" 600 python tools/bench_sparse.py --shape "$shape" --mode deduped --lanes 128 --light
done

# flat-lowering program (tpu_measurements_flat.sh) entries, light form
run dense_f32_flat 600 env BENCH_FLAT=on python bench.py
run dense_f32_marginflat 600 env BENCH_MARGIN_FLAT=on python bench.py
run dense_profile_flat 600 python tools/profile_dense.py \
    --slots 4 --rows 256 --cols 64 --only flatstack_full,flatstack_bf16
run sparse_covtype_faithful_fields_flat 600 python tools/bench_sparse.py \
    --shape covtype --format fields --flat on --light
run sparse_covtype_faithful_flat 600 python tools/bench_sparse.py \
    --shape covtype --flat on --light
run sparse_amazon_faithful_fields_flat 600 python tools/bench_sparse.py \
    --shape amazon --format fields --flat on --light
run sparse_profile_flatpairs 600 python tools/profile_sparse.py \
    --slots 4 --rows 256 --nnz 4 --cols 512 \
    --only flatpairs_margin,flatpairs_scatter
run sparse_profile_flatlanes 600 python tools/profile_sparse.py \
    --slots 4 --rows 256 --nnz 4 --cols 512 \
    --only flatlanes_margin8,scatter_onehot
run sparse_profile_marginonehot 600 python tools/profile_sparse.py \
    --slots 4 --rows 256 --nnz 4 --cols 512 \
    --only margin_onehot
run sparse_covtype_faithful_fields_lanes8_flat 600 python tools/bench_sparse.py \
    --shape covtype --format fields --lanes 8 --flat on --light
run sparse_amazon_faithful_fields_lanes8_flat 600 python tools/bench_sparse.py \
    --shape amazon --format fields --lanes 8 --flat on --light
run sparse_covtype_faithful_fields_lanes8_onehot_flat 600 python tools/bench_sparse.py \
    --shape covtype --format fields --lanes 8 --fields-scatter onehot --flat on --light
run sparse_amazon_faithful_fields_lanes8_onehot_flat 600 python tools/bench_sparse.py \
    --shape amazon --format fields --lanes 8 --fields-scatter onehot --flat on --light
run sparse_covtype_faithful_fields_mxu_flat 600 python tools/bench_sparse.py \
    --shape covtype --format fields --fields-margin onehot --fields-scatter onehot --flat on --light
run sparse_amazon_faithful_fields_mxu_flat 600 python tools/bench_sparse.py \
    --shape amazon --format fields --fields-margin onehot --fields-scatter onehot --flat on --light

run measured_arrival_agc 600 python tools/bench_measured.py --light
run dense_hbm_crosscheck 600 python tools/profile_hbm.py --light
run dynamic_mds_w30_10k 600 python tools/bench_dynamic.py --light
run dense_f32_unroll4 900 env BENCH_UNROLL=4 python bench.py
run dense_f32_unroll8 900 env BENCH_UNROLL=8 python bench.py

n_ok=$(wc -l < "$OUT")
echo "rehearsal: $n_ok entries captured in $OUT" >&2
