# Shared capture discipline for the resumable TPU measurement programs
# (tpu_measurements.sh, tpu_measurements_flat.sh). Source after setting
# OUT. Provides run <tag> <timeout_s> <cmd...>:
#
#   - already-captured tags are skipped (resume protocol; RERUN_ALL=1
#     overrides), so a wedge costs only the remaining entries;
#   - SIGINT first (python unwinds via KeyboardInterrupt so the PJRT
#     client can close its relay session — both observed relay-terminal
#     deaths followed a process killed mid-RPC); --kill-after covers a
#     child that ignores INT;
#   - ONLY exit-0 runs whose last line is valid JSON from a real TPU are
#     recorded: bench.py exits 0 with a platform:"cpu" fallback line when
#     the relay wedges mid-run, and that must stay un-captured so the
#     next healthy window retries it;
#   - wedge abort: an entry timeout (rc 124/137) OR a cpu-fallback line
#     (rc 0, platform cpu/none — the same wedge's other signature) counts
#     as wedge evidence; two consecutive pieces of evidence abort the
#     program with EX_TEMPFAIL so the watcher re-polls instead of burning
#     every remaining entry's budget against a dead relay. Any captured
#     entry, or a failure that is NOT wedge-shaped (a tool bug), resets
#     the counter.

# Persistent XLA compilation cache: every observed relay wedge (r1-r3)
# began during a fresh compile over the relay, and the per-entry budgets
# are mostly compile time. Caching compiled programs across entries and
# windows cuts both the wedge surface and the harvest time. Harmless if
# the backend declines it (JAX warns and compiles as usual).
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$(pwd)/tools/.jax_cache}"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="${JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS:-5}"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

CONSEC_WEDGE_EVIDENCE=0

run() {
  local tag="$1" tmo="$2"; shift 2
  if [ -z "${RERUN_ALL:-}" ] && [ -f "$OUT" ] \
     && grep -q "\"tag\": \"$tag\"" "$OUT"; then
    echo "=== $tag: already captured, skipping (RERUN_ALL=1 to redo)" >&2
    return
  fi
  # Absolute harvest deadline (HARVEST_DEADLINE_UNIX, set by the watcher):
  # the single-client tunnel must be FREE before the round-end driver
  # bench, so no entry may start that cannot finish in the remaining time
  # — clamp its timeout, and stop the program when <5 min remain.
  if [ -n "${HARVEST_DEADLINE_UNIX:-}" ]; then
    local rem=$(( HARVEST_DEADLINE_UNIX - $(date +%s) ))
    if [ "$rem" -lt 300 ]; then
      echo "harvest deadline reached ($rem s left) — stopping program" \
           "(resumable; nothing captured is lost)" >&2
      exit 75  # EX_TEMPFAIL
    fi
    if [ "$tmo" -gt $(( rem - 120 )) ]; then
      tmo=$(( rem - 120 ))
      echo "=== $tag: timeout clamped to $tmo s (harvest deadline)" >&2
    fi
  fi
  echo "=== $tag ($tmo s): $*" >&2
  local line rc verdict
  line="$(timeout -s INT -k 90 "$tmo" "$@" 2>"$OUT.$tag.log" | tail -1)"
  rc=$?
  # verdict: ok | cpu (exit-0 but platform cpu/none) | bad (anything else)
  verdict=bad
  if [ "$rc" -eq 0 ] && [ -n "$line" ]; then
    verdict="$(printf '%s' "$line" | python -c '
import json, sys
try:
    d = json.load(sys.stdin)
except Exception:
    print("bad"); raise SystemExit
print("cpu" if d.get("platform") in ("cpu", "none") else "ok")' 2>/dev/null)"
    [ -n "$verdict" ] || verdict=bad
  fi
  if [ "$verdict" = "ok" ]; then
    printf '{"tag": "%s", "result": %s}\n' "$tag" "$line" >> "$OUT"
    echo "$tag -> $line" >&2
    CONSEC_WEDGE_EVIDENCE=0
    return
  fi
  echo "$tag -> FAILED rc=$rc verdict=$verdict (see $OUT.$tag.log)" >&2
  if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ] || [ "$verdict" = "cpu" ]; then
    CONSEC_WEDGE_EVIDENCE=$((CONSEC_WEDGE_EVIDENCE + 1))
    if [ "$CONSEC_WEDGE_EVIDENCE" -ge 2 ]; then
      echo "two consecutive wedge signatures — relay presumed dead," \
           "aborting program (resumable; nothing captured is lost)" >&2
      exit 75  # EX_TEMPFAIL
    fi
  else
    CONSEC_WEDGE_EVIDENCE=0
  fi
}
