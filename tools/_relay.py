"""Retry wrapper for transient axon-relay failures during measurement.

The single-client TPU tunnel compiles through an HTTP endpoint that
occasionally drops a response mid-body ("read body: response body closed
before all bytes were read") without wedging the device — the very next
dispatch succeeds. A measurement tool that dies on the first such flake
forfeits its whole sweep entry (15-min timeout budget) for a 10-second
hiccup, so the warm-up/compile step of every timing loop goes through
``with_retries``. A true wedge (every retry failing) still fails fast enough
to leave the sweep's per-entry timeout unspent.
"""

from __future__ import annotations

import sys
import time
from typing import Callable, TypeVar

T = TypeVar("T")

# substrings marking relay-transport flakes (retryable), as opposed to
# genuine program errors (OOM, shape mismatch) which must propagate
_TRANSIENT = (
    "remote_compile",
    "read body",
    "response body closed",
    "connection reset",
    "connection refused",
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
)


def with_retries(fn: Callable[[], T], attempts: int = 3,
                 sleep_s: float = 15.0) -> T:
    """Run ``fn`` (a compile/dispatch thunk), retrying transient relay
    transport errors up to ``attempts`` times; non-transient errors and the
    final failure propagate unchanged."""
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:  # jax.errors.JaxRuntimeError et al.
            msg = str(e)
            transient = any(t.lower() in msg.lower() for t in _TRANSIENT)
            if not transient or i == attempts - 1:
                raise
            print(
                f"relay flake (attempt {i + 1}/{attempts}), retrying in "
                f"{sleep_s:.0f}s: {msg.splitlines()[0][:120]}",
                file=sys.stderr,
            )
            time.sleep(sleep_s)
    raise AssertionError("unreachable")
