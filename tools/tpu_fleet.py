#!/usr/bin/env python
"""TPU fleet controller — the TPU-native replacement for the reference's EC2
cluster lifecycle tool (tools/pytorch_ec2.py:935-948: launch / get_hosts /
shutdown / kill_all_python / run_command / setup_nfs).

The reference provisions EC2 spot instances with boto3, fans ssh commands out
with paramiko, and writes `hosts` / `hosts_address` inventories consumed by
``mpirun --hostfile`` (pytorch_ec2.py:656-708). On Cloud TPU none of that
survives: a TPU pod slice is ONE resource with N host VMs, created/destroyed
atomically by the `gcloud compute tpus tpu-vm` surface; ssh fan-out is
``gcloud ... ssh --worker=all``; and there is no hostfile because
``jax.distributed.initialize`` discovers the pod topology from the TPU
metadata server — every host just runs the same command (SPMD), which is the
`launch_run` subcommand here. NFS is likewise unnecessary (no shared
filesystem requirement: each host loads its own data shard), so `setup_nfs`
has no equivalent; `sync_repo` covers the code-distribution half of the
reference's remote_script.sh.

Subcommands (mirroring pytorch_ec2.py's command map):

    launch            create a TPU VM / pod slice (optionally spot/queued)
    status            describe the slice, print per-host endpoints
    get_hosts         write hosts / hosts_address inventory files (parity
                      artifact; jax.distributed does not need them)
    run_command CMD   run a shell command on every host
    kill_all_python   pkill -9 python on every host (pytorch_ec2.py:821-835)
    sync_repo DIR     scp the repo to every host (remote_script.sh parity)
    setup             install deps on every host (pre_run.sh parity)
    launch_run CMD    the mpirun replacement: run the training command on
                      every host simultaneously
    shutdown          delete the slice

All gcloud interaction is via subprocess; ``--dry-run`` prints the exact
commands instead of executing them (also the zero-egress test mode).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shlex
import subprocess
import sys
from dataclasses import dataclass, field

DEFAULT_DEPS = "jax[tpu] flax optax orbax-checkpoint scikit-learn pandas"


@dataclass
class Fleet:
    """One TPU pod slice and how to talk to it."""

    name: str
    zone: str
    project: str | None = None
    accelerator_type: str = "v4-32"
    version: str = "tpu-ubuntu2204-base"
    spot: bool = False
    dry_run: bool = False
    log: list[str] = field(default_factory=list)

    # -- plumbing -----------------------------------------------------------

    def _gcloud(self, *args: str) -> list[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", *args, f"--zone={self.zone}"]
        if self.project:
            cmd.append(f"--project={self.project}")
        return cmd

    def _run(self, cmd: list[str], capture: bool = False) -> str:
        line = " ".join(shlex.quote(c) for c in cmd)
        self.log.append(line)
        if self.dry_run:
            print(f"[dry-run] {line}")
            return ""
        try:
            res = subprocess.run(
                cmd, check=True, text=True,
                capture_output=capture,
            )
        except FileNotFoundError:
            raise SystemExit(
                "gcloud CLI not found — install the Google Cloud SDK or use "
                "--dry-run to inspect the commands this would run"
            )
        return res.stdout if capture else ""

    # -- lifecycle (pytorch_ec2.py:176-258 analogue) ------------------------

    def launch(self) -> None:
        args = [
            "create", self.name,
            f"--accelerator-type={self.accelerator_type}",
            f"--version={self.version}",
        ]
        if self.spot:
            args.append("--spot")  # preemptible, the reference's spot-request mode
        self._run(self._gcloud(*args))

    def shutdown(self) -> None:
        self._run(self._gcloud("delete", self.name, "--quiet"))

    def describe(self) -> dict:
        out = self._run(
            self._gcloud("describe", self.name, "--format=json"), capture=True
        )
        return json.loads(out) if out else {}

    # -- inventory (pytorch_ec2.py:656-708 analogue) ------------------------

    def hosts(self, info: dict | None = None) -> list[dict]:
        """Per-host endpoints: [{index, internal_ip, external_ip}, ...]."""
        info = info if info is not None else self.describe()
        out = []
        for idx, ep in enumerate(info.get("networkEndpoints", [])):
            access = ep.get("accessConfig") or {}
            out.append(
                {
                    "index": idx,
                    "internal_ip": ep.get("ipAddress"),
                    "external_ip": access.get("externalIp"),
                }
            )
        return out

    def write_hosts_files(self, info: dict | None = None, prefix: str = ".") -> list[str]:
        """Write `hosts` (ip alias lines) and `hosts_address` (bare ips) —
        the reference's inventory artifacts (pytorch_ec2.py:689-702). Kept
        for operator parity/debugging; jax.distributed needs neither."""
        paths = [f"{prefix}/hosts", f"{prefix}/hosts_address"]
        if self.dry_run and info is None:
            # the describe() this inventory would come from was skipped, so
            # the host list is empty/garbage — don't clobber real inventory
            # files with it; explicit-info callers still get real writes
            print(f"dry-run: would write {paths[0]}, {paths[1]}")
            return []
        hosts = self.hosts(info)
        with open(paths[0], "w") as f:
            for h in hosts:
                f.write(f"{h['internal_ip']} {self.name}-host{h['index']}\n")
        with open(paths[1], "w") as f:
            for h in hosts:
                f.write(f"{h['internal_ip']}\n")
        return paths

    # -- fan-out (pytorch_ec2.py:269-310, 821-879 analogue) -----------------

    def run_command(self, command: str, worker: str = "all") -> None:
        self._run(
            self._gcloud(
                "ssh", self.name, f"--worker={worker}", f"--command={command}"
            )
        )

    def kill_all_python(self) -> None:
        self.run_command("pkill -9 python || true")

    def sync_repo(self, local_dir: str, remote_dir: str = "~/erasurehead-tpu") -> None:
        self._run(
            self._gcloud(
                "scp", "--recurse", local_dir,
                f"{self.name}:{remote_dir}", "--worker=all",
            )
        )

    def setup(self, deps: str = DEFAULT_DEPS) -> None:
        """pre_run.sh parity: per-host dependency install (no conda, no MPI)."""
        self.run_command(f"pip install --upgrade {deps}")

    def launch_run(self, command: str) -> None:
        """The `mpirun -np N --hostfile ...` replacement: every host runs the
        same SPMD command; jax.distributed.initialize() inside the program
        wires the pod together from TPU metadata (parallel/backend.py)."""
        self.run_command(command)


def _validate_cli_fragment(joined: str) -> None:
    """Parse the flag tail of an embedded ``python -m erasurehead_tpu.cli``
    command against the REAL CLI parser, so a manifest can't drift from the
    actual flag surface. Raises ValueError on any unknown/invalid flag."""
    args: list[str] = []
    for tok in shlex.split(joined.split("erasurehead_tpu.cli", 1)[1]):
        if tok in ("&&", "||", ";", "|"):
            break
        args.append(tok)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from erasurehead_tpu import cli

    try:
        _, extra = cli._flags_parser().parse_known_args(args)
    except SystemExit as e:  # argparse errors exit(2)
        raise ValueError(f"embedded CLI command does not parse: {args}") from e
    if extra:
        raise ValueError(f"embedded CLI command has unknown flags: {extra}")


def validate_jobset(path: str) -> dict:
    """Offline structural validation of a JobSet manifest (the k8s path of
    the fleet lifecycle — no cluster, no CRD install needed). Checks the
    fields the JobSet controller and GKE TPU scheduling actually require,
    plus the repo-specific invariants:

      - apiVersion/kind/DNS-1123 metadata.name;
      - every replicatedJob: parallelism == completions (every host runs),
        restartPolicy, non-empty containers with name+image+command;
      - google.com/tpu requests == limits (extended resources must match);
      - gke-tpu-topology chip count == parallelism x chips-per-host;
      - every volumeMount resolves to a declared volume;
      - any embedded erasurehead_tpu.cli command parses against the real
        CLI surface (_validate_cli_fragment).

    Returns a summary dict; raises ValueError on the first violation."""
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f)

    def need(cond, msg):
        if not cond:
            raise ValueError(f"{path}: {msg}")

    need(isinstance(doc, dict), "not a YAML mapping")
    need(
        doc.get("apiVersion") == "jobset.x-k8s.io/v1alpha2",
        f"apiVersion must be jobset.x-k8s.io/v1alpha2, got {doc.get('apiVersion')!r}",
    )
    need(doc.get("kind") == "JobSet", f"kind must be JobSet, got {doc.get('kind')!r}")
    name = (doc.get("metadata") or {}).get("name", "")
    need(
        re.fullmatch(r"[a-z0-9]([-a-z0-9]{0,61}[a-z0-9])?", name or ""),
        f"metadata.name {name!r} is not a DNS-1123 label",
    )
    rjs = (doc.get("spec") or {}).get("replicatedJobs")
    need(isinstance(rjs, list) and rjs, "spec.replicatedJobs must be a non-empty list")
    summary = {"name": name, "jobs": []}
    for rj in rjs:
        need(rj.get("name"), "replicatedJob needs a name")
        jspec = (rj.get("template") or {}).get("spec") or {}
        par, comp = jspec.get("parallelism"), jspec.get("completions")
        need(isinstance(par, int) and par >= 1, f"job {rj.get('name')}: parallelism must be int >= 1")
        need(comp == par, f"job {rj.get('name')}: completions ({comp}) must equal parallelism ({par}) — every host runs the SPMD program")
        pod = (jspec.get("template") or {}).get("spec") or {}
        need(pod.get("restartPolicy") in ("Never", "OnFailure"),
             f"job {rj.get('name')}: restartPolicy must be Never/OnFailure")
        containers = pod.get("containers")
        need(isinstance(containers, list) and containers,
             f"job {rj.get('name')}: needs at least one container")
        volumes = {v.get("name") for v in pod.get("volumes") or []}
        topo = (pod.get("nodeSelector") or {}).get("cloud.google.com/gke-tpu-topology")

        def chip_count(res_block, where, cname):
            # k8s quantities arrive as YAML scalars (4 or "4"); normalize
            # to int so equivalent quantities compare equal
            q = (res_block or {}).get("google.com/tpu")
            if q is None:
                return None
            try:
                n = int(str(q))
            except ValueError:
                raise ValueError(
                    f"{path}: container {cname}: google.com/tpu {where} "
                    f"{q!r} is not an integer chip count"
                )
            if n < 1:
                raise ValueError(
                    f"{path}: container {cname}: google.com/tpu {where} "
                    f"must be >= 1, got {n}"
                )
            return n

        pod_chips = None
        for c in containers:
            need(c.get("name") and c.get("image"),
                 f"job {rj.get('name')}: container needs name and image")
            res = c.get("resources") or {}
            req = chip_count(res.get("requests"), "requests", c.get("name"))
            lim = chip_count(res.get("limits"), "limits", c.get("name"))
            # k8s defaults extended-resource requests to limits when only
            # limits is declared (the documented GKE TPU pattern), but an
            # extended resource declared only under requests is invalid
            need(lim is not None or req is None,
                 f"container {c.get('name')}: google.com/tpu declared under requests only — extended resources need limits")
            need(req is None or req == lim,
                 f"container {c.get('name')}: google.com/tpu requests must equal limits (got {req} vs {lim})")
            chips = lim
            for vm in c.get("volumeMounts") or []:
                need(vm.get("name") in volumes,
                     f"container {c.get('name')}: volumeMount {vm.get('name')!r} has no declared volume")
            if chips is not None:
                pod_chips = chips
                if topo:
                    total = 1
                    for d in str(topo).split("x"):
                        total *= int(d)
                    need(total == par * chips,
                         f"topology {topo} has {total} chips but parallelism {par} x {chips} chips/host = {par * chips}")
            cmd = c.get("command")
            need(cmd, f"container {c.get('name')}: needs a command")
            joined = " ".join(cmd) if isinstance(cmd, list) else str(cmd)
            if "erasurehead_tpu.cli" in joined:
                _validate_cli_fragment(joined)
                # cluster formation: the SPMD program needs the manual
                # coordinator env (or TPU/MEGASCALE metadata, which only
                # exists on the real nodes — the manifest cannot rely on
                # what it doesn't declare); JAX_NUM_PROCESSES must match
                # the job's parallelism or initialize() hangs at the
                # coordinator barrier
                env_vars = {
                    ev.get("name"): ev.get("value")
                    for ev in c.get("env") or []
                }
                need("JAX_COORDINATOR_ADDRESS" in env_vars,
                     f"container {c.get('name')}: training container needs "
                     "JAX_COORDINATOR_ADDRESS env for cluster formation")
                nproc = env_vars.get("JAX_NUM_PROCESSES")
                need(nproc is not None and str(nproc).isdigit()
                     and int(nproc) == par,
                     f"container {c.get('name')}: JAX_NUM_PROCESSES "
                     f"({nproc}) must equal parallelism ({par})")
        if topo:
            # a pod that selects a TPU topology but declares no google.com/tpu
            # resources would never be scheduled onto TPU by GKE (ADVICE r4)
            need(pod_chips is not None,
                 f"job {rj.get('name')}: nodeSelector requests TPU topology "
                 f"{topo} but no container declares google.com/tpu resources")
        summary["jobs"].append({"name": rj["name"], "parallelism": par,
                                "topology": topo})
    return summary


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpu_fleet",
        description=__doc__.split("\n\n")[0],
    )
    p.add_argument("--name", default="erasurehead")
    p.add_argument("--zone", default="us-central2-b")
    p.add_argument("--project", default=None)
    p.add_argument("--accelerator-type", default="v4-32")
    p.add_argument("--version", default="tpu-ubuntu2204-base")
    p.add_argument("--spot", action="store_true")
    p.add_argument("--dry-run", action="store_true")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("launch")
    sub.add_parser("status")
    gh = sub.add_parser("get_hosts")
    gh.add_argument("--prefix", default=".")
    rc = sub.add_parser("run_command")
    rc.add_argument("command")
    rc.add_argument("--worker", default="all")
    sub.add_parser("kill_all_python")
    sr = sub.add_parser("sync_repo")
    sr.add_argument("local_dir")
    sr.add_argument("--remote-dir", default="~/erasurehead-tpu")
    st = sub.add_parser("setup")
    st.add_argument("--deps", default=DEFAULT_DEPS)
    lr = sub.add_parser("launch_run")
    lr.add_argument("command")
    sub.add_parser("shutdown")
    vj = sub.add_parser("validate_jobset")
    vj.add_argument(
        "manifest",
        nargs="?",
        default=os.path.join(os.path.dirname(__file__), "k8s",
                             "jobset-v4-32.yaml"),
    )
    ns = p.parse_args(argv)

    if ns.cmd == "validate_jobset":
        print(json.dumps(validate_jobset(ns.manifest), indent=2))
        return 0

    fleet = Fleet(
        name=ns.name, zone=ns.zone, project=ns.project,
        accelerator_type=ns.accelerator_type, version=ns.version,
        spot=ns.spot, dry_run=ns.dry_run,
    )
    if ns.cmd == "launch":
        fleet.launch()
    elif ns.cmd == "status":
        info = fleet.describe()
        print(json.dumps({"state": info.get("state"), "hosts": fleet.hosts(info)}, indent=2))
    elif ns.cmd == "get_hosts":
        for path in fleet.write_hosts_files(prefix=ns.prefix):
            print(path)
    elif ns.cmd == "run_command":
        fleet.run_command(ns.command, worker=ns.worker)
    elif ns.cmd == "kill_all_python":
        fleet.kill_all_python()
    elif ns.cmd == "sync_repo":
        fleet.sync_repo(ns.local_dir, ns.remote_dir)
    elif ns.cmd == "setup":
        fleet.setup(ns.deps)
    elif ns.cmd == "launch_run":
        fleet.launch_run(ns.command)
    elif ns.cmd == "shutdown":
        fleet.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
