#!/usr/bin/env python
"""Serve load/robustness smoke on CPU (`make serve-load-smoke`).

A small-scale in-process run of the serve_load harness
(erasurehead_tpu/serve/loadgen.py) over the HTTP front, asserting the
PR's robustness bars end-to-end:

  - closed-loop fleet: every accepted request produces exactly one row
    (zero accepted-then-lost, zero duplicates), requests pack
    (dispatches < requests);
  - backpressure at ~2x capacity (max_pending far below the offered
    burst): 429s flow, every job still lands via the clients'
    deterministic capped-exponential retry-after schedule, still zero
    lost / zero duplicates;
  - fairness: with one flooding tenant, every victim tenant's goodput
    stays >= 0.5x its solo baseline (weighted-fair packing; FIFO would
    starve them behind the flood);
  - warm restart: bounce the daemon with in-process caches cleared —
    every resubmission rehydrates bitwise, the on-disk compilation
    cache gains ZERO entries;
  - the daemon's event log (request/pack/reject/stream/restart records)
    passes the schema validator, and `erasurehead-tpu report` renders
    the per-tenant reject/retry columns.

Exit 0 = all assertions hold; 1 = failure (printed).
"""

import json
import os
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU relay


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from erasurehead_tpu.obs import events as events_lib
    from erasurehead_tpu.obs import report as report_lib
    from erasurehead_tpu.obs.metrics import REGISTRY
    from erasurehead_tpu.serve import loadgen
    from erasurehead_tpu.serve import server as serve_server
    from erasurehead_tpu.serve.http_front import HttpFront

    base = tempfile.mkdtemp(prefix="eh-serve-load-smoke-")
    journal_dir = os.path.join(base, "journal")
    cache_dir = os.path.join(base, "xla")
    events_path = os.path.join(base, "events.jsonl")
    common = dict(
        scheme="naive", n_workers=4, n_stragglers=1, rounds=2,
        n_rows=64, n_cols=8, lr_schedule=0.5, add_delay=True,
        compute_mode="deduped",
    )

    def jobs_for(tenant, n, seed0=0):
        return [
            (f"{tenant}-r{k}", {**common, "seed": seed0 + k})
            for k in range(n)
        ]

    def make_front(**server_kw):
        kw = dict(
            window_s=0.05, journal_dir=journal_dir, cache_dir=cache_dir,
            max_cohort=8,
        )
        kw.update(server_kw)
        srv = serve_server.SweepServer(**kw).start()
        front = HttpFront(srv)

        def close():
            front.close()
            srv.stop()

        return srv, front, front.host, front.port, close

    with events_lib.capture(events_path):
        # ---- closed-loop fleet + packing -------------------------------
        d0 = REGISTRY.counter("serve.dispatches").value
        _s, _f, host, port, close = make_front()
        try:
            fleet = loadgen.run_fleet(
                host, port,
                {f"t{k}": jobs_for(f"t{k}", 4, seed0=100 * k)
                 for k in range(3)},
                concurrency=4,
            )
        finally:
            close()
        dispatches = REGISTRY.counter("serve.dispatches").value - d0
        assert fleet["lost"] == 0, fleet
        assert fleet["duplicates"] == 0, fleet
        rows = sum(led["rows"] for led in fleet["tenants"].values())
        assert rows == 12, fleet
        assert dispatches < 12, f"no packing: {dispatches} dispatches"
        print(f"[serve-load-smoke] closed loop: 12 rows in {dispatches} "
              f"dispatches, p99 ttfr {fleet['latency_p99_s']}s")

        # ---- backpressure at ~2x capacity ------------------------------
        _s, _f, host, port, close = make_front(max_pending=4)
        try:
            pressured = loadgen.run_fleet(
                host, port,
                {f"b{k}": jobs_for(f"b{k}", 4, seed0=1000 + 100 * k)
                 for k in range(4)},
                concurrency=4,
                max_retries=12,
            )
        finally:
            close()
        assert pressured["rejected_429s"] > 0, (
            "high-water mark never rejected under 2x load"
        )
        assert pressured["lost"] == 0, pressured
        assert pressured["duplicates"] == 0, pressured
        for led in pressured["tenants"].values():
            assert led["rows"] == led["jobs"] - led["rejected_final"], led
            assert led["rejected_final"] == 0, (
                f"retry schedule exhausted: {led}"
            )
        print(f"[serve-load-smoke] backpressure: "
              f"{pressured['rejected_429s']} 429s, "
              f"{pressured['retries']} retries, 0 lost, 0 dups")

        # ---- fairness under one flooding tenant ------------------------
        # journal OFF for these phases: rehydration of the solo phase's
        # rows would fake the contended goodput — this measures pure
        # scheduling (all signatures already warm from the phases above)
        import functools

        fair = loadgen.fairness_run(
            functools.partial(make_front, journal_dir=None),
            victim_jobs={
                f"v{k}": jobs_for(f"v{k}", 3, seed0=5000 + 100 * k)
                for k in range(2)
            },
            flood_jobs=jobs_for("flood", 24, seed0=9000),
            flood_concurrency=24,
        )
        assert fair["min_goodput_ratio"] is not None, fair
        assert fair["min_goodput_ratio"] >= 0.5, (
            f"fairness bar missed: min goodput ratio "
            f"{fair['min_goodput_ratio']} < 0.5 ({fair['goodput_ratio']})"
        )
        print(f"[serve-load-smoke] fairness: victim goodput ratios "
              f"{fair['goodput_ratio']} (bar 0.5)")

        # ---- warm restart ----------------------------------------------
        # fresh seeds: the first pass must genuinely dispatch (and write
        # the on-disk cache) so the bounce proves rehydration, not reuse
        restart = loadgen.restart_run(
            make_front,
            {f"r{k}": jobs_for(f"r{k}", 4, seed0=7000 + 100 * k)
             for k in range(2)},
            cache_dir=cache_dir,
        )
        assert restart["bitwise_mismatches"] == 0, restart
        assert restart["resumed"] == restart["rows_resubmitted"], restart
        assert restart["new_compile_cache_entries"] == 0, restart
        print(f"[serve-load-smoke] restart: "
              f"{restart['rows_resubmitted']} rows rehydrated bitwise, "
              f"0 new compile-cache entries, "
              f"{restart['restart_wall_s']}s wall")

    # ---- event log + report ------------------------------------------
    errors = events_lib.validate_file(events_path)
    assert errors == [], errors[:5]
    assert report_lib.main([events_path, "--validate"]) == 0
    print("[serve-load-smoke] PASS")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"FAIL: {e}", file=sys.stderr)
        sys.exit(1)
