#!/usr/bin/env bash
# Follow-up measurement program for the flat-stack GLM lowering
# (parallel/step.make_flat_grad_fn, landed mid-round after the margin
# profile put the flat 2-D matmul at the raw-stream floor). Same resumable
# tagged-append protocol as tpu_measurements.sh; the watcher runs this
# program FIRST (its entries decide production defaults). Never run two
# programs concurrently — the relay serves one client.
#
#   bash tools/tpu_measurements_flat.sh [out.jsonl]
set -u -o pipefail
cd "$(dirname "$0")/.."
OUT="${1:-tools/measurements.jsonl}"
export PYTHONPATH="${PYTHONPATH:-}:$(pwd)"

. "$(dirname "$0")/measure_lib.sh"

# Ordered by decision value for a short window (VERDICT r4 ordering):
# 0: the structure-independent bandwidth crosscheck FIRST — it decides
#    whether the dense ">650 steps/s unreachable" ceiling claim stands
#    or every margin variant gets re-raced (VERDICT r4 #2);
# 1-2: validate the fields fix (auto->flat flipped on the r3 evidence) at
#      both canonical shapes; then the fields x lanes constellation;
#      then marginflat for MARGIN_FLAT_DEFAULT; then the rest.
run dense_hbm_crosscheck 900 python tools/profile_hbm.py
run sparse_covtype_faithful_fields_flat 1200 python tools/bench_sparse.py \
    --shape covtype --format fields --flat on
run sparse_amazon_faithful_fields_flat  1200 python tools/bench_sparse.py \
    --shape amazon --format fields --flat on
# composed lowering (landed mid-round-3): lane-replicated pair-table
# margin gathers — the two measured wins stacked. Candidate to push
# faithful covtype past 3x the reference rate (fields_flat measured
# 2.994x; profiled margin drop 54.5 -> ~21 ms predicts ~3.5x).
run sparse_covtype_faithful_fields_lanes8_flat 1200 python tools/bench_sparse.py \
    --shape covtype --format fields --lanes 8 --flat on
run sparse_amazon_faithful_fields_lanes8_flat  1200 python tools/bench_sparse.py \
    --shape amazon --format fields --lanes 8 --flat on
# one-hot MXU scatter stacked on the lane margin: the first candidate
# that attacks the serialized scatter-add bound structurally (per-field
# segment-sum as compare + matmul, ops/features._onehot_fields_rmatvec)
run sparse_covtype_faithful_fields_lanes8_onehot_flat 1200 python tools/bench_sparse.py \
    --shape covtype --format fields --lanes 8 --fields-scatter onehot --flat on
run sparse_amazon_faithful_fields_lanes8_onehot_flat  1200 python tools/bench_sparse.py \
    --shape amazon --format fields --lanes 8 --fields-scatter onehot --flat on
# full-MXU sparse step: one-hot matmuls in BOTH directions — zero
# serialized lookups (ops/features._onehot_fields_matvec/_rmatvec)
run sparse_covtype_faithful_fields_mxu_flat 1200 python tools/bench_sparse.py \
    --shape covtype --format fields --fields-margin onehot --fields-scatter onehot --flat on
run sparse_amazon_faithful_fields_mxu_flat  1200 python tools/bench_sparse.py \
    --shape amazon --format fields --fields-margin onehot --fields-scatter onehot --flat on
run dense_f32_flat       1800 env BENCH_FLAT=on python bench.py
# hybrid: flat 2-D margin matmul + batched per-slot transpose — the two
# profiled winners combined (margin_matmul2d 1.587 ms; transpose near-
# free per two_pass-vs-margin_only). Races the captured dense_f32.
run dense_f32_marginflat 1800 env BENCH_MARGIN_FLAT=on python bench.py
# bf16 data (the measured 581-vs-462 win) x the hybrid margin candidate:
# if marginflat wins f32, this is the composed production frontier
run dense_bf16_marginflat 1800 env BENCH_MARGIN_FLAT=on BENCH_DTYPE=bfloat16 python bench.py
# measured-arrival AGC (VERDICT r4 #4): worker_timeset as a device
# measurement; writes artifacts/measured_arrival_tpu.json. Also listed in
# tpu_measurements.sh — the tag-skip protocol makes that a no-op.
run measured_arrival_agc 900 python tools/bench_measured.py
# scan-unroll race: the candidate fix for the in-scan bandwidth gap
# (126 GB/s in-scan vs 819 peak) — XLA fuses/overlaps consecutive
# rounds. Races the captured dense_f32 per-slot baseline directly.
run dense_f32_unroll4 1800 env BENCH_UNROLL=4 python bench.py
run dense_f32_unroll8 1800 env BENCH_UNROLL=8 python bench.py
# repeat captures of the round-3 single-window headline wins (VERDICT r4
# #8): same commands, fresh tags, so each headline sparse number carries
# window variance like the dense ones do (462-530 across windows).
run sparse_covtype_faithful_fields_flat_rep 1200 python tools/bench_sparse.py \
    --shape covtype --format fields --flat on
run sparse_amazon_faithful_fields_flat_rep  1200 python tools/bench_sparse.py \
    --shape amazon --format fields --flat on
run dense_profile_flat   1200 python tools/profile_dense.py \
    --only flatstack_full,flatstack_bf16
run sparse_profile_flatpairs 1200 python tools/profile_sparse.py \
    --only flatpairs_margin,flatpairs_scatter
# composed flat x lanes margin at production shapes, plus the one-hot
# MXU scatter (segment-sum as compare + matmul — the first candidate
# that attacks the serialized scatter-add bound structurally)
run sparse_profile_flatlanes 1200 python tools/profile_sparse.py \
    --only flatlanes_margin8,scatter_onehot
run sparse_profile_marginonehot 1200 python tools/profile_sparse.py \
    --only margin_onehot
run sparse_covtype_faithful_flat        1200 python tools/bench_sparse.py \
    --shape covtype --flat on
run sparse_covtype_deduped_fields_flat  1200 python tools/bench_sparse.py \
    --shape covtype --mode deduped --format fields --flat on
run sparse_amazon_faithful_flat         1200 python tools/bench_sparse.py \
    --shape amazon --flat on
run sparse_amazon_deduped_fields_flat   1200 python tools/bench_sparse.py \
    --shape amazon --mode deduped --format fields --flat on
run sparse_covtype_deduped_fields_lanes8_flat 1200 python tools/bench_sparse.py \
    --shape covtype --mode deduped --format fields --lanes 8 --flat on
run sparse_amazon_deduped_fields_lanes8_flat  1200 python tools/bench_sparse.py \
    --shape amazon --mode deduped --format fields --lanes 8 --flat on
run dense_bf16_flat      1800 env BENCH_FLAT=on BENCH_DTYPE=bfloat16 python bench.py
run dense_f32_deduped_flat 1800 env BENCH_FLAT=on BENCH_MODE=deduped python bench.py
# deduped x full-MXU: if the MXU lowerings win faithful, these decide
# the fastest-honest-mode production default
run sparse_covtype_deduped_fields_mxu_flat 1200 python tools/bench_sparse.py \
    --shape covtype --mode deduped --format fields --fields-margin onehot --fields-scatter onehot --flat on
run sparse_amazon_deduped_fields_mxu_flat  1200 python tools/bench_sparse.py \
    --shape amazon --mode deduped --format fields --fields-margin onehot --fields-scatter onehot --flat on

echo "flat measurements appended to $OUT" >&2
