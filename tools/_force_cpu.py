"""Import FIRST to force a standalone tool onto the host CPU backend.

This image's sitecustomize force-registers the remote-TPU ("axon") PJRT
plugin and sets JAX_PLATFORMS=axon, so merely exporting JAX_PLATFORMS=cpu
does nothing — the same dance tests/conftest.py does for pytest is needed
for ad-hoc tool runs (compile-time experiments, rehearsals) that must not
dial the single-client TPU tunnel (a second client wedges it).

    import _force_cpu  # noqa: F401  (before anything imports jax)
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from jax._src import xla_bridge as _xb  # noqa: E402

# pop only the tunnel plugin; removing "tpu" would unregister the platform
# name itself (see tests/conftest.py)
_xb._backend_factories.pop("axon", None)

assert jax.devices()[0].platform == "cpu"
