#!/usr/bin/env bash
# Repeat-capture pass (VERDICT r5 #5): every tag that GATES a production
# default or appears as a BASELINE.md headline gets a second, independent
# capture under a `_rep2` suffix, so no default flip or headline number
# ever rests on n=1 again. Same resumable tagged-append protocol as
# tpu_measurements.sh (already-captured rep2 tags are skipped on rerun);
# run it AFTER the base programs in a healthy window —
# tools/harvest_decisions.py then marks each decision with its capture
# count n and the cross-window spread, and flags n=1 decisions as
# provisional.
#
#   bash tools/tpu_measurements_rep2.sh [out.jsonl]
set -u -o pipefail
cd "$(dirname "$0")/.."
OUT="${1:-tools/measurements.jsonl}"
export PYTHONPATH="${PYTHONPATH:-}:$(pwd)"

. "$(dirname "$0")/measure_lib.sh"

# --- dense decision gates (MARGIN_FLAT_DEFAULT / margin_cols / unroll) ---
run dense_f32_rep2             1800 python bench.py
run dense_f32_marginflat_rep2  1800 env BENCH_MARGIN_FLAT=on python bench.py
run dense_f32_margincols8_rep2 1800 env BENCH_MARGIN_COLS=8 python bench.py
run dense_f32_unroll4_rep2     1800 env BENCH_UNROLL=4 python bench.py
run dense_f32_unroll8_rep2     1800 env BENCH_UNROLL=8 python bench.py

# --- bf16 frontier -------------------------------------------------------
run dense_bf16_rep2            1800 env BENCH_DTYPE=bfloat16 python bench.py
run dense_bf16_flat_rep2       1800 env BENCH_FLAT=on BENCH_DTYPE=bfloat16 python bench.py
run dense_bf16_marginflat_rep2 1800 env BENCH_MARGIN_FLAT=on BENCH_DTYPE=bfloat16 python bench.py

# --- ring stack mode (the memory-side candidate) -------------------------
run dense_f32_ring_rep2        1800 env BENCH_STACK=ring python bench.py
run dense_bf16_ring_rep2       1800 env BENCH_STACK=ring BENCH_DTYPE=bfloat16 python bench.py

# --- PR-6 memory-system levers (BASELINE.md queued-measurement note) -----
# double-buffered transport gates RING_PIPELINE_DEFAULT; the int8 rows
# carry the fidelity extra (eval-loss delta vs the f32 stack); nodonate
# is the donation before-row now that the canonical run donates
run dense_f32_ringpipe_rep2    1800 env BENCH_STACK=ring BENCH_RING_PIPELINE=on python bench.py
run dense_int8_ring_rep2       1800 env BENCH_STACK=ring BENCH_STACK_DTYPE=int8 python bench.py
run dense_int8_ringpipe_rep2   1800 env BENCH_STACK=ring BENCH_RING_PIPELINE=on BENCH_STACK_DTYPE=int8 python bench.py
run dense_int8_rep2            1800 env BENCH_STACK_DTYPE=int8 python bench.py
run dense_f32_nodonate_rep2    1800 env BENCH_DONATE=off python bench.py

# --- composed out-of-core streaming (ISSUE 17 headliners) ----------------
run dense_f32_streamring_rep2  1800 env BENCH_STACK=ring BENCH_RESIDENCY=streamed BENCH_STREAM_WINDOW=6 python bench.py
run dense_int8_streamring_rep2 1800 env BENCH_STACK=ring BENCH_STACK_DTYPE=int8 BENCH_RESIDENCY=streamed BENCH_STREAM_WINDOW=6 python bench.py
run cohort_stream_rep2         1800 env BENCH_STACK=ring BENCH_STACK_DTYPE=int8 BENCH_RESIDENCY=streamed BENCH_STREAM_WINDOW=6 BENCH_OUTOFCORE_COHORT=16 python bench.py

# --- fields constellation (per-shape default gates) ----------------------
for shape in covtype amazon; do
  run "sparse_${shape}_faithful_fields_flat_rep2" 1200 python tools/bench_sparse.py \
      --shape "$shape" --format fields --flat on
  run "sparse_${shape}_faithful_fields_lanes8_flat_rep2" 1200 python tools/bench_sparse.py \
      --shape "$shape" --format fields --lanes 8 --flat on
  run "sparse_${shape}_faithful_fields_lanes8_onehot_flat_rep2" 1200 python tools/bench_sparse.py \
      --shape "$shape" --format fields --lanes 8 --fields-scatter onehot --flat on
  run "sparse_${shape}_faithful_fields_mxu_flat_rep2" 1200 python tools/bench_sparse.py \
      --shape "$shape" --format fields --fields-margin onehot --fields-scatter onehot --flat on
done

# --- deduped routing gates ----------------------------------------------
for shape in covtype amazon; do
  run "sparse_${shape}_deduped_rep2" 1200 python tools/bench_sparse.py \
      --shape "$shape" --mode deduped
  run "sparse_${shape}_deduped_fields_flat_rep2" 1200 python tools/bench_sparse.py \
      --shape "$shape" --mode deduped --format fields --flat on
  run "sparse_${shape}_deduped_fields_lanes8_flat_rep2" 1200 python tools/bench_sparse.py \
      --shape "$shape" --mode deduped --format fields --lanes 8 --flat on
  run "sparse_${shape}_deduped_fields_mxu_flat_rep2" 1200 python tools/bench_sparse.py \
      --shape "$shape" --mode deduped --format fields --fields-margin onehot --fields-scatter onehot --flat on
done

# --- BASELINE.md headliners without a decision gate ----------------------
# (the *_rep tags in tpu_measurements_flat.sh give these n=2; rep2 makes
# the spread three-way when the window allows)
run sparse_covtype_faithful_rep2 1200 python tools/bench_sparse.py --shape covtype
run sparse_amazon_faithful_rep2  1200 python tools/bench_sparse.py --shape amazon

# --- autotune decision gates (ISSUE 19): the fused_decode verdicts flip
# resolve_block_decode / supports_fused at this shape, so they need n>=2;
# the rep2 pass re-races into a THROWAWAY cache (the decision record is
# the measurements.jsonl line — harvest_decisions.py computes the spread;
# only the base pass's cache feeds resolution)
run fused_decode_rep2 1800 env ERASUREHEAD_TUNE_CACHE=/tmp/eh-tune-rep2.json \
    python -m erasurehead_tpu.cli tune --json \
    --race block_decode --race glm_fused \
    --model deepmlp --workers 8 --rows 4096 --cols 256 --rounds 8

echo "rep2 measurements appended to $OUT" >&2
