#!/usr/bin/env bash
# Watch the axon relay for a healthy window; when one appears, run the
# resumable measurement sweep (tools/tpu_measurements.sh). Probe is a
# SUBPROCESS jax.devices() with a hard timeout — a wedged relay hangs the
# probe child, never this script. Logs to tools/relay_watch.log.
#
#   bash tools/relay_watch.sh [max_hours]
set -u
cd "$(dirname "$0")/.."
LOG=tools/relay_watch.log
MAX_HOURS="${1:-11}"
DEADLINE=$(( $(date +%s) + MAX_HOURS * 3600 ))
export PYTHONPATH="${PYTHONPATH:-}:$(pwd)"

probe() {
  timeout 90 python - <<'EOF' >/dev/null 2>&1
import subprocess, sys
r = subprocess.run(
    [sys.executable, "-c",
     "import jax; ds=jax.devices(); assert ds and ds[0].platform=='tpu', ds; print(ds)"],
    capture_output=True, text=True, timeout=80)
sys.exit(r.returncode)
EOF
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if probe; then
    echo "$(date -Is) relay HEALTHY — running sweep" >> "$LOG"
    bash tools/tpu_measurements.sh >> "$LOG" 2>&1
    # Count remaining queued tags; sweep skips captured ones, so a clean
    # pass through means we are done.
    if bash -c 'grep -c FAILED tools/relay_watch.log >/dev/null'; then :; fi
    missing=$(python tools/sweep_status.py 2>/dev/null || echo "?")
    echo "$(date -Is) sweep pass done; missing entries: $missing" >> "$LOG"
    if [ "$missing" = "0" ]; then
      echo "$(date -Is) ALL ENTRIES CAPTURED — watcher exiting" >> "$LOG"
      exit 0
    fi
  else
    echo "$(date -Is) relay wedged/down (probe timeout)" >> "$LOG"
  fi
  sleep 240
done
echo "$(date -Is) watcher deadline reached" >> "$LOG"
