#!/usr/bin/env bash
# Watch the axon relay for a healthy window; when one appears, run the
# resumable measurement programs (tpu_measurements_flat.sh first — its
# entries decide production defaults — then tpu_measurements.sh). Probe
# is a SUBPROCESS jax.devices() with a hard timeout — a wedged relay
# hangs the probe child, never this script. Logs to tools/relay_watch.log.
#
#   bash tools/relay_watch.sh [max_hours]
set -u
cd "$(dirname "$0")/.."
LOG=tools/relay_watch.log
MAX_HOURS="${1:-11}"
DEADLINE=$(( $(date +%s) + MAX_HOURS * 3600 ))
# Absolute cutoff (epoch seconds) after which the tunnel must be free —
# the round-end driver bench is the next single client. Clamp the poll
# deadline to it and export so measure_lib clamps per-entry timeouts.
if [ -n "${HARVEST_DEADLINE_UNIX:-}" ]; then
  [ "$DEADLINE" -gt "$HARVEST_DEADLINE_UNIX" ] && DEADLINE="$HARVEST_DEADLINE_UNIX"
  export HARVEST_DEADLINE_UNIX
fi
export PYTHONPATH="${PYTHONPATH:-}:$(pwd)"
# persistent compile cache (see measure_lib.sh) — also covers the fresh
# bench.py below
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$(pwd)/tools/.jax_cache}"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="${JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS:-5}"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

probe() {
  timeout 90 python - <<'EOF' >/dev/null 2>&1
import subprocess, sys
r = subprocess.run(
    [sys.executable, "-c",
     "import jax; ds=jax.devices(); assert ds and ds[0].platform=='tpu', ds; print(ds)"],
    capture_output=True, text=True, timeout=80)
sys.exit(r.returncode)
EOF
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if probe; then
    echo "$(date -Is) relay HEALTHY — running sweeps" >> "$LOG"
    # flat-lowering program first: its entries decide the production
    # defaults (dense flat race, the sparse fields fix validation)
    bash tools/tpu_measurements_flat.sh >> "$LOG" 2>&1
    # re-probe between programs — a mid-sweep wedge otherwise burns the
    # second program's per-entry timeouts against a dead relay (and would
    # fall through to a doomed bench.py below: skip to the next poll)
    if ! probe; then
      echo "$(date -Is) relay wedged mid-window — re-polling" >> "$LOG"
      sleep 240
      continue
    fi
    bash tools/tpu_measurements.sh >> "$LOG" 2>&1
    missing=$(python tools/sweep_status.py 2>/dev/null || echo "?")
    echo "$(date -Is) sweep pass done; missing entries: $missing" >> "$LOG"
    if [ "$missing" = "0" ]; then
      # fresh round-3 dense capture: the sweep skips the r2-captured
      # dense_f32 tag, but bench.py refreshes BENCH_TPU_LAST.json, which
      # the driver's end-of-round bench reports if the relay is wedged
      # then. 2700s > bench.py's worst-case internal attempt budget
      # (~120+900 + 120+420 + 120+900), so its one-JSON-line contract
      # cannot be killed mid-fallback.
      if [ -n "${HARVEST_DEADLINE_UNIX:-}" ] \
         && [ $(( HARVEST_DEADLINE_UNIX - $(date +%s) )) -lt 2760 ]; then
        echo "$(date -Is) sweep done but <46 min to harvest deadline —" \
             "skipping fresh bench (driver's round-end bench covers it);" \
             "watcher exiting" >> "$LOG"
        exit 0
      fi
      echo "$(date -Is) running fresh bench.py for BENCH_TPU_LAST" >> "$LOG"
      timeout 2700 python bench.py >> "$LOG" 2>&1
      echo "$(date -Is) fresh bench exit=$? — watcher exiting" >> "$LOG"
      exit 0
    fi
  else
    echo "$(date -Is) relay wedged/down (probe timeout)" >> "$LOG"
  fi
  sleep 240
done
echo "$(date -Is) watcher deadline reached" >> "$LOG"
