#!/usr/bin/env python
"""Serve-fleet drill with REAL process kills (`make fleet-smoke`).

The fleet contract end-to-end, replicas as actual subprocesses:

  leg 1  a ONE-replica fleet serves tenant alice's three requests —
         the bitwise baseline AND the single-replica goodput
         measurement for the scaling leg.
  leg 2  a THREE-replica fleet behind the router; chaos
         ``kill:fleet_replica:2`` is armed on exactly the replica the
         hash ring routes alice to (computed up front — the ring is
         deterministic). Alice's warm request completes (dispatch #1),
         then two more same-signature requests arrive: dispatch #2
         kills that replica via ``os._exit`` mid-dispatch. The
         supervisor's probes miss K consecutive times -> declare_dead
         (the validator refuses an earlier declaration), the next live
         peer in ring order ADOPTS the dead WAL (O_EXCL sentinel,
         owner-/healthz refusal, digest dedup) and replays the
         acceptances; every row reaches the client EXACTLY once through
         the router's re-dialing stream fan-in, bitwise equal to leg
         1's (science columns).
  leg 3  on the two survivors: a rolling deploy under 4-tenant packable
         load at ~2x capacity — each replica drained, bounced, WAL
         replayed, re-admitted — with ZERO accepted-then-lost rows and
         ZERO duplicates; then a steady-state 4-tenant run measures
         two-replica goodput against leg 1's single-replica figure.

Typed ``fleet`` events from the supervisor, router, and every replica
are schema-validated (obs/events.validate_lines). Exit 0 = PASS
(summary JSON on stdout); 1 = failure.

Perf figures (goodput scaling, deploy-vs-steady TTFR p99) are recorded
in the summary; set ``FLEET_SMOKE_STRICT=1`` to also assert the ISSUE
bars (scaling >= 1.7x, p99 <= 2x) on hosts with the cores to meet them.
"""

import json
import os
import sys
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU relay

CFG = {
    "scheme": "naive", "n_workers": 4, "n_stragglers": 1, "rounds": 2,
    "n_rows": 64, "n_cols": 8, "lr_schedule": 0.5, "add_delay": True,
    "compute_mode": "deduped",
}
KILL_EXIT = 43  # utils/chaos.KILL_EXIT
K = 3  # evidential misses before death


def science(row):
    from erasurehead_tpu.train import journal as journal_lib

    return json.dumps(journal_lib.science_row(row), sort_keys=True)


def alice_rows(router_host, router_port, expect_kill=False):
    """Serve alice's warm/b/c through the router; returns rows by label
    plus the raw delivered count (exactly-once check)."""
    from erasurehead_tpu.serve.client import HttpServeClient

    c = HttpServeClient(router_host, router_port, "alice")
    c.submit("warm", {**CFG, "seed": 0}, max_retries=8)
    res = c.result(timeout=900)
    assert res["status"] == "ok", res
    rows = {res["label"]: res["row"]}
    delivered = 1
    c.submit("b", {**CFG, "seed": 1}, max_retries=8)
    c.submit("c", {**CFG, "seed": 2}, max_retries=8)
    deadline = time.monotonic() + 900
    while {"b", "c"} - set(rows) and time.monotonic() < deadline:
        try:
            res = c.result(timeout=10)
        except Exception:  # noqa: BLE001 — Empty while adoption replays
            continue
        assert res["status"] == "ok", res
        rows[res["label"]] = res["row"]
        delivered += 1
    assert {"warm", "b", "c"} <= set(rows), sorted(rows)
    # grace window: any duplicate delivery (a second stream replaying
    # the same request_id) would land here and bump `delivered`
    t_end = time.monotonic() + 3
    while time.monotonic() < t_end:
        try:
            c.result(timeout=1)
            delivered += 1
        except Exception:  # noqa: BLE001 — Empty is the success case
            pass
    c.close()
    return rows, delivered


def four_tenant_load(router_host, router_port, jobs_per_tenant=4,
                     concurrency=2, seed_base=10):
    """PR-13 loadgen at ~2x capacity: 4 tenants, packable jobs.

    ``seed_base`` keeps each leg's digests distinct — identical digests
    would rehydrate from the fleet's journals instead of dispatching,
    and a goodput figure made of journal hits measures nothing."""
    from erasurehead_tpu.serve import loadgen

    tenant_jobs = {
        f"t{i}": [
            (f"j{i}_{j}", {**CFG, "seed": seed_base + i * 64 + j})
            for j in range(jobs_per_tenant)
        ]
        for i in range(4)
    }
    t0 = time.monotonic()
    out = loadgen.run_fleet(
        router_host, router_port, tenant_jobs,
        concurrency=concurrency, max_retries=12, timeout=900,
    )
    elapsed = time.monotonic() - t0
    rows = sum(led.get("rows", 0) for led in out["tenants"].values())
    out["goodput_rows_per_s"] = (
        round(rows / elapsed, 4) if elapsed > 0 else None
    )
    return out


def validate_events(paths):
    from erasurehead_tpu.obs import events as events_lib

    errs = []
    for p in paths:
        if not os.path.exists(p):
            continue
        with open(p) as f:
            errs += [f"{os.path.basename(p)}: {e}"
                     for e in events_lib.validate_lines(f)]
    return errs


def main():
    import tempfile

    from erasurehead_tpu.obs import events as events_lib
    from erasurehead_tpu.serve.fleet import FleetSupervisor
    from erasurehead_tpu.serve.router import HashRing, affinity_key

    base = tempfile.mkdtemp(prefix="eh-fleet-smoke-")
    cache = os.path.join(base, "xla-cache")  # shared across all legs
    summary = {}
    sup_events = os.path.join(base, "supervisor.events.jsonl")

    # ---- leg 1: single-replica baseline + goodput ------------------------
    sup1 = FleetSupervisor(
        n=1, base_dir=os.path.join(base, "one"), k=K,
        probe_interval_s=0.3, cache_dir=cache,
        extra_args=("--dispatch-workers", "1"),
    )
    sup1.start()
    try:
        baseline, delivered = alice_rows(
            sup1.router.host, sup1.router.port
        )
        assert delivered == 3, f"baseline delivered {delivered} != 3"
        solo = four_tenant_load(sup1.router.host, sup1.router.port,
                                seed_base=10)
        assert solo["lost"] == 0 and solo["duplicates"] == 0, solo
        goodput_1 = solo["goodput_rows_per_s"]
    finally:
        sup1.stop()
    summary["leg1"] = {"goodput_1_replica_rows_per_s": round(goodput_1, 3)}
    print(f"leg1 PASS: baseline + 1-replica goodput {goodput_1:.3f} rows/s",
          file=sys.stderr)

    # ---- leg 2: kill a replica mid-dispatch; peer adopts its WAL ---------
    victim = HashRing(["r0", "r1", "r2"]).lookup(
        affinity_key("alice", {**CFG, "seed": 0})
    )
    with events_lib.capture(sup_events):
        sup = FleetSupervisor(
            n=3, base_dir=os.path.join(base, "fleet"), k=K,
            probe_interval_s=0.3, cache_dir=cache,
            chaos={victim: "kill:fleet_replica:2"},
            extra_args=("--dispatch-workers", "1"),
        )
        sup.start()
        try:
            rows, delivered = alice_rows(
                sup.router.host, sup.router.port, expect_kill=True
            )
            # exactly-once: 3 labels, 3 deliveries, no dup in the grace
            # window
            assert delivered == 3, f"delivered {delivered} != 3"
            for label in ("warm", "b", "c"):
                assert science(rows[label]) == science(baseline[label]), (
                    f"row {label!r} not bitwise vs baseline"
                )
            victim_rep = sup.replicas[victim]
            rc = victim_rep.proc.poll()
            assert rc == KILL_EXIT, (
                f"victim {victim} exit {rc} != chaos KILL_EXIT"
            )
            assert victim in sup._dead_handled, "death never declared"
            sentinel = victim_rep.wal_path + ".adopted"
            assert os.path.exists(sentinel), "WAL never adopted"
            assert sup.router.adoptions_total >= 1

            # the double-adoption race regression, cross-process for
            # real: a second adopter must lose on the O_EXCL sentinel
            from erasurehead_tpu.serve.wal import (
                IntakeWAL,
                WalAdoptionError,
            )

            late = IntakeWAL(os.path.join(base, "late-adopter"))
            try:
                late.adopt(victim_rep.wal_path)
                raise AssertionError("second adoption must be refused")
            except WalAdoptionError:
                pass

            # ---- leg 3: rolling deploy under load on the survivors ---
            deploy_ledger = {}

            def deploy():
                time.sleep(2.0)  # let the load get going first
                deploy_ledger.update(sup.rolling_deploy())

            t = threading.Thread(target=deploy)
            t.start()
            load = four_tenant_load(
                sup.router.host, sup.router.port,
                jobs_per_tenant=6, concurrency=2, seed_base=1000,
            )
            t.join(timeout=600)
            assert not t.is_alive(), "rolling deploy wedged"
            assert load["lost"] == 0, f"deploy lost rows: {load['lost']}"
            assert load["duplicates"] == 0, (
                f"deploy duplicated rows: {load['duplicates']}"
            )
            assert len(deploy_ledger) == 2, deploy_ledger
            deploy_p99 = load.get("latency_p99_s")

            # steady state on the bounced pair: TTFR reference + the
            # 2-replica goodput figure (fresh seeds — journal hits from
            # an earlier leg would fake the scaling number)
            steady = four_tenant_load(
                sup.router.host, sup.router.port,
                jobs_per_tenant=4, concurrency=2, seed_base=2000,
            )
            goodput_2 = steady["goodput_rows_per_s"]
            assert steady["lost"] == 0 and steady["duplicates"] == 0
            steady_p99 = steady.get("latency_p99_s")
        finally:
            sup.stop()

    # ---- events validate (supervisor + every replica's own journal) -----
    paths = [sup_events] + [
        r.events_path for r in sup.replicas.values()
    ]
    errs = validate_events(paths)
    assert not errs, "\n".join(errs[:10])
    sup_recs = [
        json.loads(ln) for ln in open(sup_events) if ln.strip()
    ]
    fleet_recs = [r for r in sup_recs if r.get("type") == "fleet"]
    deaths = [r for r in fleet_recs if r["action"] == "declare_dead"]
    assert deaths and all(r["streak"] >= r["k"] for r in deaths), deaths
    phases = {
        (r["replica"], r.get("phase"))
        for r in fleet_recs
        if r["action"] == "deploy_phase"
    }
    survivors = sorted(set(sup.replicas) - {victim})
    for name in survivors:
        for ph in ("drain", "stop", "ready"):
            assert (name, ph) in phases, f"missing {ph} for {name}"
    adopt_recs = [
        json.loads(ln)
        for name in survivors
        for ln in open(sup.replicas[name].events_path)
        if '"fleet"' in ln
    ]
    adopted = [
        r for r in adopt_recs
        if r.get("action") == "adopt" and r.get("replica") == victim
    ]
    assert len(adopted) == 1, f"adoptions != 1: {adopted}"
    assert adopted[0].get("records", 0) >= 1, adopted

    scaling = goodput_2 / goodput_1 if goodput_1 else None
    p99_ratio = (
        deploy_p99 / steady_p99
        if deploy_p99 and steady_p99
        else None
    )
    summary.update({
        "leg2": {
            "victim": victim,
            "deaths_declared": len(deaths),
            "adopted_records": adopted[0].get("records"),
            "bitwise": True,
        },
        "leg3": {
            "deploy": deploy_ledger,
            "deploy_lost": load["lost"],
            "deploy_duplicates": load["duplicates"],
            "deploy_latency_p99_s": deploy_p99,
            "steady_latency_p99_s": steady_p99,
            "p99_deploy_over_steady": (
                round(p99_ratio, 3) if p99_ratio else None
            ),
            "goodput_2_replicas_rows_per_s": round(goodput_2, 3),
            "goodput_scaling_1_to_2": (
                round(scaling, 3) if scaling else None
            ),
        },
    })
    if os.environ.get("FLEET_SMOKE_STRICT") == "1":
        assert scaling and scaling >= 1.7, f"scaling {scaling} < 1.7"
        assert p99_ratio and p99_ratio <= 2.0, (
            f"deploy p99 {p99_ratio}x steady > 2x"
        )
    print("leg2+leg3 PASS", file=sys.stderr)
    print(json.dumps({"fleet_smoke": "PASS", **summary}, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
