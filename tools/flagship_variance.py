#!/usr/bin/env python
"""Error bars for the flagship AGC-vs-EGC-vs-uncoded claim.

The reference's straggler schedule is one fixed universe (delays seeded by
iteration index, src/naive.py:141-147), so its headline comparison is a
single draw. This study reruns the canonical W=30 / s=2 / collect=15 /
AGD / 100-round comparison under N independent delay universes — universe
0 IS the reference's exact schedule; universe u>0 seeds iteration i with
``i + u*1_000_003`` (distinct MT19937 streams) — all schemes sharing each
universe's schedule, and reports the spread of time-to-target and
speedup-vs-naive. Simulated-clock science: platform-independent,
reproduces bit-for-bit anywhere.

Writes artifacts/flagship_seed_variance.json.

Usage: python tools/flagship_variance.py [--universes 5] [--rounds 100]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--universes", type=int, default=5)
    ap.add_argument("--rounds", type=int, default=100)
    ap.add_argument("--out", default="artifacts/flagship_seed_variance.json")
    ns = ap.parse_args()

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.train import experiments
    from erasurehead_tpu.utils.config import RunConfig

    # data shape matches artifacts/flagship_canonical_w30.json (13200x100);
    # absolute times still differ from that artifact (different lr preset),
    # so the quantity of interest here is the cross-universe SPREAD of the
    # relative speedups, not agreement with the canonical absolute numbers
    W, S, COLLECT, R = 30, 2, 15, ns.rounds
    base = dict(
        n_workers=W, rounds=R, add_delay=True, n_rows=13200, n_cols=100,
        update_rule="AGD", lr_schedule=1.0, seed=0,
    )
    configs = {
        "naive": RunConfig(scheme="naive", n_stragglers=0, **base),
        "cyccoded_s2": RunConfig(scheme="cyccoded", n_stragglers=S, **base),
        "repcoded_s2": RunConfig(scheme="repcoded", n_stragglers=S, **base),
        "agc_collect15": RunConfig(
            scheme="approx", n_stragglers=S, num_collect=COLLECT, **base
        ),
    }
    data = generate_gmm(base["n_rows"], base["n_cols"], n_partitions=W, seed=0)

    from erasurehead_tpu.parallel import straggler

    per_universe: list[dict] = []
    for u in range(ns.universes):
        delays = straggler.reference_delay_schedule(
            R, W, seed_offset=u * 1_000_003
        )
        summaries = experiments.compare(configs, data, arrivals=delays)
        naive_t = next(
            s.time_to_target for s in summaries if s.label == "naive"
        )
        row = {"universe": u, "reference_schedule": u == 0}
        for s in summaries:
            # time_to_target is None when a scheme never reaches the
            # 1.05x-naive loss target in this universe — record the miss
            tt = s.time_to_target
            row[s.label] = {
                "time_to_target_s": None if tt is None else round(tt, 4),
                "speedup_vs_naive": (
                    None if tt is None or naive_t is None
                    else round(naive_t / tt, 3)
                ),
                "final_train_loss": round(s.final_train_loss, 6),
                "final_auc": round(s.final_auc, 6),
            }
        per_universe.append(row)
        print(f"universe {u}: " + ", ".join(
            f"{k}={v['speedup_vs_naive']}x" for k, v in row.items()
            if isinstance(v, dict)
        ), file=sys.stderr)


    def _summary(vals):
        xs = np.array([v for v in vals if v is not None], dtype=float)
        if xs.size == 0:
            return {"mean": None, "std": None, "min": None, "max": None,
                    "missed_target": len(list(vals))}
        return {
            "mean": round(float(xs.mean()), 4),
            # std needs >= 2 samples; null (not NaN) keeps the JSON strict
            "std": round(float(xs.std(ddof=1)), 4) if xs.size > 1 else None,
            "min": round(float(xs.min()), 4),
            "max": round(float(xs.max()), 4),
        }

    stats = {}
    for label in configs:
        stats[label] = {
            "time_to_target_s": _summary(
                [r[label]["time_to_target_s"] for r in per_universe]
            ),
            "speedup_vs_naive": _summary(
                [r[label]["speedup_vs_naive"] for r in per_universe]
            ),
        }

    out = {
        "config": {
            "n_workers": W, "n_stragglers": S, "num_collect": COLLECT,
            "rounds": R, "n_rows": base["n_rows"], "n_cols": base["n_cols"],
            "update_rule": "AGD", "universes": ns.universes,
            "universe_0": "the reference's exact iteration-seeded schedule",
        },
        "stats": stats,
        "per_universe": per_universe,
    }
    out_dir = os.path.dirname(ns.out)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    with open(ns.out, "w") as f:
        json.dump(out, f, indent=1)
    print(f"variance study -> {ns.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
