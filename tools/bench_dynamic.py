"""Fully on-device control plane at canonical scale (VERDICT r4 #9).

Runs the 10k-round W=30 cyclic-MDS configuration under
``trainer.train_dynamic`` — arrivals, Waitany collection masks, AND the
MDS decode (via the f64-precomputed ``codes.MdsDecodeTable`` gather) all
traced inside ONE jitted ``lax.scan``, with zero host round-trips between
iterations. This is the silicon demonstration that closes the loop on the
reference's per-iteration host lstsq (src/coded.py:147-149): the same
10 000 decode-and-update rounds the reference spends 10 000 Python/MPI
iterations on become a single XLA dispatch.

CPU correctness for this exact path is pinned in
tests/test_dynamic.py (TestMdsDecodeTable + the W=30 convergence test);
this tool measures it at canonical scale and rounds.

Protocol (measure_lib contract): exit 0, last stdout line is one JSON
object with a "platform" key. train_dynamic's wall clock includes the
compile of its scan, so the run is performed twice — the first call pays
the compile (and seeds the persistent XLA cache), the reported rate is
the warm second call.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=30)
    ap.add_argument("--stragglers", type=int, default=3)
    ap.add_argument("--rows", type=int, default=132000)
    ap.add_argument("--cols", type=int, default=128)
    ap.add_argument("--rounds", type=int, default=10_000)
    ap.add_argument("--light", action="store_true",
                    help="rehearsal shape (CPU: seconds, not minutes)")
    args = ap.parse_args()
    if args.light:
        args.rows, args.cols, args.rounds = args.workers * 16, 16, 50

    # the warm-run protocol below relies on the persistent compile cache:
    # each train_dynamic call jits a fresh closure, so without this the
    # second call recompiles the whole scan and "warm" measures compile
    # again (measure_lib.sh exports the same default for sweep runs)
    import os

    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )
    # threshold 0, forced even under measure_lib's exported 5 s default:
    # the scan may compile in under 5 s, and an un-persisted cold compile
    # makes the warm call silently recompile (fresh closure per call)
    os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"

    import jax
    import jax.numpy as jnp
    import numpy as np

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.models.glm import LogisticModel
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import RunConfig

    platform = jax.devices()[0].platform
    W, s = args.workers, args.stragglers
    print(
        f"bench_dynamic: platform={platform} W={W} s={s} rows={args.rows} "
        f"cols={args.cols} rounds={args.rounds} scheme=cyccoded(table)",
        file=sys.stderr,
    )
    cfg = RunConfig(
        scheme="cyccoded", n_workers=W, n_stragglers=s, rounds=args.rounds,
        n_rows=args.rows, n_cols=args.cols, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0,
    )
    data = generate_gmm(args.rows, args.cols, n_partitions=W, seed=0)

    t0 = time.perf_counter()
    cold = trainer.train_dynamic(cfg, data)  # pays the scan compile
    warm = trainer.train_dynamic(cfg, data)  # reported rate
    total = time.perf_counter() - t0

    # reference-protocol effective rate on the same simulated clock
    # (bench.py's convention: rounds / summed per-round Waitany times)
    ref_rate = (
        args.rounds / warm.sim_total_time if warm.sim_total_time > 0 else 0.0
    )
    hist = np.asarray(warm.params_history)
    model = LogisticModel()
    Xt, yt = jnp.asarray(data.X_test), jnp.asarray(data.y_test)
    first = float(model.loss_mean(jnp.asarray(hist[0]), Xt, yt))
    last = float(model.loss_mean(jnp.asarray(hist[-1]), Xt, yt))

    result = {
        "metric": f"dynamic_mds_w{W}_steps_per_sec_{args.rounds}rounds",
        "value": round(float(warm.steps_per_sec), 3),
        "unit": "iterations/sec",
        "vs_baseline": round(float(warm.steps_per_sec / ref_rate), 3)
        if ref_rate
        else None,
        "platform": platform,
        "cold_steps_per_sec": round(float(cold.steps_per_sec), 3),
        "scan_wall_s": round(float(warm.wall_time), 4),
        "first_loss": round(first, 6),
        "last_loss": round(last, 6),
        "converged": bool(np.isfinite(hist).all() and last < first * 0.8),
        "rounds": args.rounds,
        "wall_total_s": round(total, 1),
    }
    print(
        f"bench_dynamic: warm={warm.steps_per_sec:.1f} it/s "
        f"(cold {cold.steps_per_sec:.1f}) ref_rate={ref_rate:.3f} it/s "
        f"loss {first:.4f}->{last:.4f}",
        file=sys.stderr,
    )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
