#!/usr/bin/env python
"""Smoke-check the deep-model coded-training fast path on CPU
(`make deep-smoke`).

Drives a W=8 ATTENTION cohort end-to-end through the trajectory-batched
engine with per-layer (blockwise) gradient coding forced on, then
asserts the deep-path contract:

  - the whole 2-scheme x 2-seed attention cohort runs as ONE compiled
    dispatch (cohort.dispatches counter; lowering = layer_block_vmap);
  - the blockwise layer decode is BITWISE identical to the monolithic
    treewise decode over the same per-partition gradient pytrees, on the
    cohort's own first-round collection weights;
  - cohort trajectories match sequential train() of the same configs to
    float tolerance (reduction order only);
  - the events.jsonl — cohort record, per-trajectory round/decode
    streams, and a layer-tagged decode-error-vs-depth series
    (obs/events.emit_layer_decode_chunks) — passes the schema check.

Exit 0 = all assertions hold; 1 = failure (printed).
"""

import os
import sys
import tempfile

# runnable from anywhere without an install (the tools/ convention)
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU relay


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from erasurehead_tpu.data.sharding import partition_stack
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.obs import decode as obs_decode
    from erasurehead_tpu.obs import events as events_lib
    from erasurehead_tpu.obs.metrics import REGISTRY
    from erasurehead_tpu.ops import blocks as blocks_lib
    from erasurehead_tpu.parallel import collect, step as step_lib
    from erasurehead_tpu.train import cache, trainer
    from erasurehead_tpu.utils.config import RunConfig

    W, rounds = 8, 4
    n_rows, n_cols = 128, 32  # n_cols % d_in == 0 (rows -> token sequences)
    data = generate_gmm(n_rows, n_cols, n_partitions=W, seed=0)
    common = dict(
        model="attention", n_workers=W, n_stragglers=1, rounds=rounds,
        n_rows=n_rows, n_cols=n_cols, update_rule="GD", lr_schedule=0.1,
        add_delay=True, compute_mode="deduped", layer_coding="on",
    )
    cfgs = [
        RunConfig(**{**common, "scheme": s, "seed": sd, **extra})
        for s, extra in (("approx", {"num_collect": 6}), ("repcoded", {}))
        for sd in (0, 1)
    ]

    cache.clear()
    for name in ("cohort.dispatches", "cohort.trajectories"):
        REGISTRY.counter(name).reset()
    events_path = os.path.join(
        tempfile.mkdtemp(prefix="eh-deep-smoke-"), "events.jsonl"
    )
    failures = []
    with events_lib.capture(events_path):
        results = trainer.train_cohort(cfgs, data)
        # layer-tagged decode-error-vs-depth series from the first
        # trajectory's own partition gradient blocks at its final params
        res = results[0]
        model = trainer.build_model(res.config)
        params0 = model.init_params(jax.random.key(res.config.seed), n_cols)
        spec = blocks_lib.model_block_spec(model, params0)
        Xp, yp = partition_stack(data, res.layout.n_partitions)
        table = blocks_lib.partition_block_table(
            model, spec, res.final_params, Xp, yp
        )
        sched = collect.build_schedule(
            res.config.scheme, trainer.default_arrivals(res.config),
            res.layout, num_collect=res.config.num_collect,
            deadline=res.config.deadline, decode=res.config.decode,
        )
        errs = obs_decode.block_decode_error(
            res.layout, sched.message_weights, table
        )
        events_lib.emit_layer_decode_chunks(
            res.run_id, errs["per_block"], trajectory="smoke"
        )

    # ---- one dispatch, blockwise lowering
    dispatches = REGISTRY.counter("cohort.dispatches").value
    if dispatches != 1:
        failures.append(f"cohort.dispatches={dispatches}, expected 1")
    lowering = results[0].cache_info.get("cohort_lowering")
    if lowering != "layer_block_vmap":
        failures.append(f"cohort_lowering={lowering!r}, expected layer_block_vmap")

    # ---- bitwise layer-decode pin: blockwise einsum == treewise decode
    # over the SAME per-partition gradient pytrees, on the cohort's own
    # first-round fold weights
    per_part = jax.vmap(
        lambda X, y: model.grad_sum(
            jax.tree.map(jnp.asarray, res.final_params),
            jnp.asarray(X), jnp.asarray(y),
        )
    )(jnp.asarray(Xp), jnp.asarray(yp))
    slot_w = np.asarray(
        step_lib.expand_slot_weights(
            sched.message_weights, res.layout.coeffs,
            np.asarray(res.layout.slot_is_coded),
        )
    )
    pw = jnp.asarray(res.layout.fold_slot_weights(slot_w)[0], jnp.float32)
    tree_dec = step_lib._weighted_tree_sum(pw, per_part, "p")
    tbl = jax.vmap(lambda g: blocks_lib.tree_to_blocks(g, spec))(per_part)
    blk_dec = blocks_lib.blocks_to_tree(
        jnp.einsum(
            "p,plk->lk", pw.astype(tbl.dtype), tbl,
            precision=lax.Precision.HIGHEST,
        ),
        spec,
    )
    for a, b in zip(jax.tree.leaves(tree_dec), jax.tree.leaves(blk_dec)):
        if np.asarray(a).tobytes() != np.asarray(b).tobytes():
            failures.append(
                "blockwise layer decode != treewise decode bitwise"
            )
            break

    # ---- cohort trajectories match sequential train()
    for cfg, r in zip(cfgs, results):
        single = trainer.train(cfg, data)
        for a, b in zip(
            jax.tree.leaves(r.params_history),
            jax.tree.leaves(single.params_history),
        ):
            if not np.allclose(
                np.asarray(a, np.float64), np.asarray(b, np.float64),
                rtol=5e-4, atol=5e-5,
            ):
                failures.append(
                    f"cohort trajectory {cfg.scheme.value}/s{cfg.seed} "
                    "drifted from sequential train()"
                )
                break

    # ---- events validate, layer tags present
    schema_errors = events_lib.validate_file(events_path)
    failures.extend(f"events schema: {e}" for e in schema_errors)
    import json as json_lib

    with open(events_path) as f:
        recs = [json_lib.loads(line) for line in f if line.strip()]
    layers = {r.get("layer") for r in recs if r["type"] == "decode"}
    layers.discard(None)
    if len(layers) != spec.n_blocks:
        failures.append(
            f"expected {spec.n_blocks} layer-tagged decode streams, got "
            f"{sorted(layers)}"
        )

    print(
        f"deep-smoke: {len(cfgs)} attention trajectories -> "
        f"{dispatches} dispatch ({lowering}); {spec.n_blocks} coded "
        f"blocks; mean per-block decode error "
        f"{float(np.mean(errs['per_block'])):.4f}"
    )
    print(f"events -> {events_path}")
    for f in failures:
        print(f"FAIL: {f}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
