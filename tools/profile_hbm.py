"""Independent cross-check of the dense bandwidth-floor claim (VERDICT r3
#3). The in-scan ``raw_stream`` probe (tools/profile_dense.py) measured a
126 GB/s elementwise floor with the SAME lax.scan structure as the
production step it bounds; this tool measures the ceiling two ways that
share none of that structure:

1. out-of-scan stream probes — single-dispatch jitted passes over a
   ``--gb``-sized array, timed host-side over reps: ``reduce_stream``
   (read + scalar reduce, nbytes of traffic) and ``copy_stream``
   (read + write, 2x nbytes). No scan, no carry, work sized so dispatch
   latency is noise (~1.5 GB at >100 GB/s is >10 ms per dispatch).
2. a jax.profiler device trace of the production-shaped two-pass dense
   gradient (out of scan, dispatch-per-iteration), parsed from the
   xplane.pb (tensorflow profiler protos ship in this image) for
   device-side op durations and any bytes/bandwidth counters the backend
   exposes.

Prints one JSON line (measure_lib contract). Every sub-probe degrades to
an ``*_error`` field instead of failing the entry.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from _relay import with_retries

HI = lax.Precision.HIGHEST


def _median_time(fn, *a, reps=8):
    with_retries(lambda: jax.block_until_ready(fn(*a)))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*a))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def stream_probes(results, gb):
    n_rows = max(1, int(gb * 1e9 / 4) // 128)
    x = jnp.ones((n_rows, 128), jnp.float32)
    nbytes = x.size * 4

    @jax.jit
    def reduce_stream(x, c):
        return jnp.sum(x * c)

    @jax.jit
    def copy_stream(x, c):
        return x * c

    c = jnp.float32(1.000001)
    t = _median_time(reduce_stream, x, c)
    results["reduce_stream_ms"] = round(t * 1e3, 3)
    results["reduce_stream_gbps"] = round(nbytes / t / 1e9, 1)
    t = _median_time(copy_stream, x, c)
    results["copy_stream_ms"] = round(t * 1e3, 3)
    results["copy_stream_gbps"] = round(2 * nbytes / t / 1e9, 1)
    for k in ("reduce_stream_gbps", "copy_stream_gbps"):
        print(f"profile_hbm: {k} = {results[k]}", file=sys.stderr)


def _parse_xplane(logdir):
    """Summarize every .xplane.pb under a jax.profiler logdir: device
    plane names, per-plane busy time, top ops by self duration, and any
    stat whose name mentions bytes/bandwidth/memory."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(
        os.path.join(logdir, "plugins", "profile", "*", "*.xplane.pb")
    )
    if not paths:
        return {"error": "no xplane.pb produced"}
    # multi-host / per-device dumps emit one xplane.pb each — parse them
    # all and record the count so partial coverage is visible (ADVICE r4)
    summary = {"planes": [], "xplane_files": len(paths)}
    planes = []
    for p in sorted(paths):
        xspace = xplane_pb2.XSpace()
        with open(p, "rb") as f:
            xspace.ParseFromString(f.read())
        planes.extend(xspace.planes)
    for plane in planes:
        ev_names = {m.id: m.name for m in plane.event_metadata.values()}
        st_names = {m.id: m.name for m in plane.stat_metadata.values()}
        op_ps: dict[str, int] = {}
        byte_stats: dict[str, float] = {}
        span_ps = 0
        for line in plane.lines:
            if not line.events:
                continue
            start = min(e.offset_ps for e in line.events)
            end = max(e.offset_ps + e.duration_ps for e in line.events)
            span_ps = max(span_ps, end - start)
            for e in line.events:
                name = ev_names.get(e.metadata_id, str(e.metadata_id))
                op_ps[name] = op_ps.get(name, 0) + e.duration_ps
                for s in e.stats:
                    sn = st_names.get(s.metadata_id, "")
                    if any(k in sn.lower()
                           for k in ("byte", "bandwidth", "memory", "flop")):
                        v = (s.value.int64_value or s.value.uint64_value
                             or s.value.double_value)
                        byte_stats[sn] = byte_stats.get(sn, 0) + float(v)
        top = sorted(op_ps.items(), key=lambda kv: -kv[1])[:8]
        summary["planes"].append({
            "name": plane.name,
            "busy_ms": round(sum(op_ps.values()) / 1e9, 3),
            "span_ms": round(span_ps / 1e9, 3),
            "top_ops_ms": {k: round(v / 1e9, 3) for k, v in top},
            "byte_stats": byte_stats or None,
        })
    return summary


def trace_production_step(results, slots, rows, cols, iters):
    """The production two-pass dense gradient at the bench shape, out of
    scan (one dispatch per iteration), under a device trace."""
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((slots, rows, cols)), jnp.float32)
    y = jnp.asarray(
        rng.choice([-1.0, 1.0], (slots, rows)).astype(np.float32)
    )

    @jax.jit
    def grad(beta):
        p = jnp.einsum("mrf,f->mr", X, beta, precision=HI)
        r = y / (jnp.exp(p * y) + 1.0)
        g = jnp.einsum("mrf,mr->mf", X, r, precision=HI)
        return beta * 0.999 + g.sum(0) / rows

    beta = jnp.zeros(cols, jnp.float32)
    with_retries(lambda: jax.block_until_ready(grad(beta)))
    t0 = time.perf_counter()
    for _ in range(iters):
        beta = grad(beta)
    jax.block_until_ready(beta)
    host_ms = (time.perf_counter() - t0) / iters * 1e3
    results["prod_step_outscan_ms"] = round(host_ms, 3)
    # two X passes per step is the model the in-scan number assumed
    results["prod_step_outscan_gbps"] = round(
        2 * X.size * 4 / (host_ms / 1e3) / 1e9, 1
    )
    print(
        f"profile_hbm: prod step out-of-scan {host_ms:.3f} ms "
        f"({results['prod_step_outscan_gbps']} GB/s two-pass)",
        file=sys.stderr,
    )
    with tempfile.TemporaryDirectory() as logdir:
        jax.profiler.start_trace(logdir)
        b = jnp.zeros(cols, jnp.float32)
        for _ in range(iters):
            b = grad(b)
        jax.block_until_ready(b)
        jax.profiler.stop_trace()
        results["trace"] = _parse_xplane(logdir)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--gb", type=float, default=1.5,
                    help="stream-probe array size in GB")
    ap.add_argument("--slots", type=int, default=90)
    ap.add_argument("--rows", type=int, default=4400)
    ap.add_argument("--cols", type=int, default=128)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--light", action="store_true",
                    help="rehearsal shape (CPU: seconds, not minutes)")
    args = ap.parse_args()
    if args.light:
        args.gb, args.slots, args.rows, args.iters = 0.02, 4, 256, 5

    results = {"platform": jax.devices()[0].platform, "gb": args.gb}
    print(f"profile_hbm: platform={results['platform']}", file=sys.stderr)
    try:
        stream_probes(results, args.gb)
    except Exception as e:  # noqa: BLE001 — degrade, keep the entry
        results["stream_error"] = repr(e)[:300]
    try:
        trace_production_step(
            results, args.slots, args.rows, args.cols, args.iters
        )
    except Exception as e:  # noqa: BLE001
        results["trace_error"] = repr(e)[:300]
    print(json.dumps(results))


if __name__ == "__main__":
    main()
