"""Race the fused pallas GLM-gradient kernel against XLA's two-pass lowering
on real TPU, at the bench shape, and report timings as one JSON line.

VERDICT r1 item 3: settle the pallas kernel. The MXU-dot variant measured
slower than XLA (2.7ms vs 2.05ms on v5e); this times the exact-f32 VPU
variant (ops/kernels.py) so supports_fused can be flipped or the kernel
demoted based on a committed number.

Usage: python tools/kernel_race.py [--rows 4400] [--cols 128] [--slots 90]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from _relay import with_retries


def time_scanned(
    grad_fn, beta, X, y, w, iters: int, reps: int = 5
) -> tuple[float, float]:
    """(seconds per gradient application, median whole-dispatch wall),
    measured INSIDE one dispatch.

    The TPU here is reached through a remote relay whose per-dispatch round
    trip is ~60-70ms — individually timed calls measure the network, not the
    kernel. So run ``iters`` applications in one jitted lax.scan (feeding
    each gradient back into beta so nothing can be elided) and divide.
    """

    @jax.jit
    def many(b0):
        def body(b, _):
            g = grad_fn(b, X, y, w)
            # feed back through a norm so beta stays O(1) across iters
            return g / (jnp.linalg.norm(g) + 1.0), None

        bN, _ = jax.lax.scan(body, b0, None, length=iters)
        return bN

    with_retries(lambda: jax.block_until_ready(many(beta)))  # compile + warm
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(many(beta))
        times.append(time.perf_counter() - t0)
    return float(np.median(times)) / iters, float(np.median(times))


def main() -> None:
    ap = argparse.ArgumentParser()
    # bench shape: W=30 workers x (s+1)=3 slots, 132k rows / 30 workers
    ap.add_argument("--slots", type=int, default=90)
    ap.add_argument("--rows", type=int, default=4400)
    ap.add_argument("--cols", type=int, default=128)
    ap.add_argument("--iters", type=int, default=50)
    # bfloat16 stores the stack in half the bytes: the one configuration
    # where the single-pass kernel's halved traffic could beat XLA's
    # (already well-fused) two-pass f32 lowering (VERDICT r2 item 8)
    ap.add_argument("--dtype", choices=("float32", "bfloat16"),
                    default="float32")
    # CPU rehearsal hook: run the pallas kernel in interpret mode so the
    # whole script (arg surface, bf16 operand plumbing, result schema) can
    # be validated off-TPU before spending a healthy relay window on it.
    # Timings in this mode are meaningless; the JSON carries the flag.
    ap.add_argument("--interpret", action="store_true")
    args = ap.parse_args()

    from erasurehead_tpu.ops import kernels

    platform = jax.devices()[0].platform
    M, R, F = args.slots, args.rows, args.cols
    print(f"race: platform={platform} M={M} R={R} F={F}", file=sys.stderr)

    key = jax.random.PRNGKey(0)
    kx, ky, kb, kw = jax.random.split(key, 4)
    dt = jnp.dtype(args.dtype)
    X = jax.random.normal(kx, (M, R, F), jnp.float32).astype(dt)
    y = jnp.sign(jax.random.normal(ky, (M, R), jnp.float32))
    beta = jax.random.normal(kb, (F,), jnp.float32)
    w = jax.random.uniform(kw, (M,), jnp.float32)

    def xla_bf16(b, X, y, w, kind):
        # the production bf16-data lowering (ops/features.py rule): cast the
        # tiny vector operands to the data dtype so the stack streams as
        # stored, accumulate in f32 on the MXU
        p = jnp.einsum("mrf,f->mr", X, b.astype(X.dtype),
                       preferred_element_type=jnp.float32)
        yf = y.astype(jnp.float32)
        if kind == "logistic":
            s = -yf / (jnp.exp(p * yf) + 1.0)
        else:
            s = -2.0 * (yf - p)
        s = s * w[:, None]
        return jnp.einsum("mrf,mr->f", X, s.astype(X.dtype),
                          preferred_element_type=jnp.float32)

    results = {}
    for kind in ("logistic", "linear"):
        fused = lambda b, X, y, w, k=kind: kernels.fused_glm_grad(
            b, X, y, w, k, interpret=args.interpret
        )
        if dt == jnp.bfloat16:
            xla_hi = lambda b, X, y, w, k=kind: xla_bf16(b, X, y, w, k)
        else:
            xla_hi = lambda b, X, y, w, k=kind: kernels.reference_glm_grad(
                b, X, y, w, k
            )
        # first dispatch = first compile over the relay; retry transient
        # transport flakes like the timing loops do
        g_f = with_retries(lambda: fused(beta, X, y, w))
        g_x = with_retries(lambda: xla_hi(beta, X, y, w))
        rel = float(
            jnp.linalg.norm(g_f - g_x) / (jnp.linalg.norm(g_x) + 1e-30)
        )
        t_f, wall_f = time_scanned(fused, beta, X, y, w, iters=args.iters)
        t_x, wall_x = time_scanned(xla_hi, beta, X, y, w, iters=args.iters)
        results[kind] = {
            "pallas_ms": round(t_f * 1e3, 4),
            "xla_ms": round(t_x * 1e3, 4),
            "speedup": round(t_x / t_f, 3),
            "rel_err": rel,
        }
        # a whole-dispatch wall below the relay's ~60 ms round trip is
        # physically impossible on this path — the work was elided or the
        # relay short-circuited (observed once: the bf16-tallR logistic XLA
        # leg read 0.0005 ms/iter). Flag the leg rather than record a
        # bogus number. Applies only behind the axon relay (its env marker,
        # see tools/_force_cpu.py) — a genuine local TPU with a small
        # shape can legitimately finish a dispatch far faster.
        import os

        if os.environ.get("PALLAS_AXON_POOL_IPS") and not args.interpret:
            floor = 0.05
            if wall_f < floor or wall_x < floor:
                results[kind]["invalid"] = (
                    f"dispatch wall pallas={wall_f:.4f}s xla={wall_x:.4f}s "
                    f"below the {floor:.2f}s relay round-trip floor"
                )
        print(f"race: {kind}: {results[kind]}", file=sys.stderr)

    x_bytes = M * R * F * dt.itemsize
    out = {
        "platform": platform,
        "shape": [M, R, F],
        "dtype": str(dt),
        "x_mib": round(x_bytes / 2**20, 1),
        **({"interpret": True} if args.interpret else {}),
        **results,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
