#!/usr/bin/env python
"""Out-of-core harness: shard-store -> streamed sweep -> kill -> resume
(``make outofcore-smoke``).

Proves the streamed-residency resilience contract end-to-end with REAL
process deaths, which the in-process tests cannot do. Three stories:

  1. **per-trajectory** (``--batch off``) — the child writes a shard
     store (data/store.py), opens it, and drives a journaled straggler
     sweep whose every trajectory runs ``stack_residency="streamed"``
     with a multi-partition-window prefetch pipeline (stream_window=1 <
     P, so data/prefetch.py is on the hot path). The kill leg arms
     ``ERASUREHEAD_CHAOS=kill:prefetch:N`` with N sized so exactly one
     trajectory's row reached the journal first; the resume leg reopens
     the SAME store directory (content digest -> identical journal
     keys), SKIPS the journaled row, trains the rest, and must produce
     summary rows BITWISE identical to the baseline.
  2. **cohort** (the ``--batch auto`` default) — the same three streamed
     trajectories share a static signature (scheme is not in it; the
     deduped partition-major stack is scheme-agnostic), so the sweep
     dispatches them as ONE windowed cohort scan
     (trainer._train_cohort_streamed): one dispatch stages n_windows
     windows TOTAL, not per trajectory. ``kill:prefetch:2`` therefore
     dies mid-cohort with NOTHING journaled, and resume re-trains the
     whole cohort to rows bitwise identical to the cohort baseline.
     The stats file pins the shape: cohort.dispatches == 1,
     cohort.trajectories == 3.
  3. **ring** (``--ring``) — a faithful streamed+ring sweep (cyccoded
     s=1 and s=2, stream_window=2) runs the assignment-aware window
     plan end-to-end: each trajectory's slot-group windows stage their
     assignment halo in ring-hop order. Differing straggler budgets
     mean differing assignments mean differing cohort signatures, so
     these trajectories never share a compiled scan
     (cohort.dispatches == 0) — the negative the packer contract pins.

Every journal is schema-checked with the same validator as every other
event log. Exit 0 = all invariants held.

Usage: python tools/outofcore_smoke.py [--rounds 8] [--workers 4]
       (the --child form is the harness's internal sweep runner)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

KILL_EXIT = 43  # erasurehead_tpu.utils.chaos.KILL_EXIT (no jax import here)


def child(ns) -> int:
    """One journaled STREAMED sweep run: the unit the orchestrator
    kills/resumes. The first child invocation writes the shard store;
    later ones (the resume leg) reopen it from disk, so the rehydration
    path crosses a real process boundary."""
    from erasurehead_tpu.data import store as store_lib
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.train import experiments
    from erasurehead_tpu.train import journal as journal_lib
    from erasurehead_tpu.utils.config import RunConfig

    W = ns.workers
    rows = W * 16
    if os.path.exists(os.path.join(ns.store, store_lib.META_NAME)):
        store = store_lib.open_store(ns.store)
    else:
        src = generate_gmm(rows, 8, n_partitions=W, seed=0)
        store = store_lib.write_store(src, ns.store, W)
    data = store.dataset()
    if ns.ring:
        # faithful streamed+ring: the assignment-aware window plan on
        # the hot path (slot-group windows, ring-hop halo staging)
        base = RunConfig(
            scheme="cyccoded", n_workers=W, n_stragglers=1,
            rounds=ns.rounds, n_rows=rows, n_cols=8, lr_schedule=1.0,
            update_rule="GD", add_delay=True, seed=0,
            stack_residency="streamed", stream_window=2,
            stack_mode="ring",
        )
        sweep = {"cyccoded": [1, 2]}
    else:
        base = RunConfig(
            scheme="naive", n_workers=W, n_stragglers=0,
            num_collect=W // 2, rounds=ns.rounds, n_rows=rows, n_cols=8,
            lr_schedule=1.0, update_rule="GD", add_delay=True, seed=0,
            compute_mode="deduped", stack_residency="streamed",
            stream_window=1,
        )
        sweep = {
            "naive": [0],
            "cyccoded": [1],
            "avoidstragg": [1],
        }
    journal = journal_lib.SweepJournal(ns.journal, resume=ns.resume)
    try:
        summaries = experiments.straggler_sweep(
            base, data, sweep, journal=journal, batch=ns.batch
        )
    finally:
        journal.close()
    with open(ns.out, "w") as f:
        json.dump(
            [journal_lib.science_row(s.row()) for s in summaries],
            f, indent=1,
        )
    if ns.stats:
        from erasurehead_tpu.obs.metrics import REGISTRY

        snap = REGISTRY.snapshot()
        with open(ns.stats, "w") as f:
            json.dump(
                {
                    "cohort.dispatches": snap.get("cohort.dispatches", 0),
                    "cohort.trajectories": snap.get(
                        "cohort.trajectories", 0
                    ),
                },
                f,
            )
    return 0


def _fires_per_trajectory(ns) -> int:
    """Prefetch windows one SEQUENTIAL streamed trajectory stages: the
    trainer's chunking arithmetic (trainer._train_streamed) with
    stream_window=1, so n_windows = P = workers and chunk length
    L = rounds // n_windows. Only valid for ``--batch off`` legs — a
    cohort dispatch stages this many windows for the WHOLE cohort."""
    n_windows = ns.workers
    L = max(1, ns.rounds // n_windows)
    return len(range(0, ns.rounds, L))


def _run_child(workdir, ns, leg, journal_dir, out, store, resume=False,
               chaos=None, batch="off", ring=False,
               stats=None) -> subprocess.CompletedProcess:
    cmd = [
        sys.executable, os.path.abspath(__file__), "--child",
        "--journal", journal_dir, "--out", out, "--store", store,
        "--rounds", str(ns.rounds), "--workers", str(ns.workers),
        "--batch", batch,
    ]
    if resume:
        cmd.append("--resume")
    if ring:
        cmd.append("--ring")
    if stats:
        cmd.extend(["--stats", stats])
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("ERASUREHEAD_CHAOS", None)
    if chaos:
        env["ERASUREHEAD_CHAOS"] = chaos
    print(f"[outofcore-smoke] {leg}: {' '.join(cmd[2:])}"
          + (f"  ERASUREHEAD_CHAOS={chaos}" if chaos else ""),
          file=sys.stderr)
    return subprocess.run(cmd, env=env, cwd=workdir)


def _load(path):
    with open(path) as f:
        return json.load(f)


def _assert_rows_equal(a, b, leg: str) -> None:
    if a == b:
        return
    for ra, rb in zip(a, b):
        if ra != rb:
            diff = {
                k: (ra.get(k), rb.get(k))
                for k in set(ra) | set(rb)
                if ra.get(k) != rb.get(k)
            }
            raise SystemExit(
                f"[outofcore-smoke] FAIL ({leg}): row {ra.get('label')!r} "
                f"differs from baseline: {diff}"
            )
    raise SystemExit(f"[outofcore-smoke] FAIL ({leg}): row sets differ")


def _journal_rows(jdir: str) -> int:
    jpath = os.path.join(jdir, "sweep_journal.jsonl")
    if not os.path.exists(jpath):
        # a kill mid-cohort can land before the journal's first write
        return 0
    return sum(
        1 for line in open(jpath)
        if line.strip() and json.loads(line)["type"] == "sweep_trajectory"
    )


def _validate_journal(jdir: str, leg: str) -> None:
    from erasurehead_tpu.obs import events as events_lib

    jpath = os.path.join(jdir, "sweep_journal.jsonl")
    if not os.path.exists(jpath):
        return
    errors = events_lib.validate_file(jpath)
    if errors:
        raise SystemExit(
            f"[outofcore-smoke] FAIL ({leg}): journal invalid: {errors}"
        )


def _kill_resume_story(work, ns, store, tag, batch, chaos_count,
                       expect_journaled) -> list:
    """Baseline -> kill -> resume for one dispatch mode; returns the
    baseline science rows after asserting the whole invariant chain."""
    base_out = os.path.join(work, f"rows_{tag}_base.json")
    res_out = os.path.join(work, f"rows_{tag}_resumed.json")
    stats = os.path.join(work, f"stats_{tag}.json")
    jdir_base = os.path.join(work, f"journal_{tag}_base")
    jdir_kill = os.path.join(work, f"journal_{tag}_kill")

    p = _run_child(work, ns, f"{tag}-baseline", jdir_base, base_out,
                   store, batch=batch, stats=stats)
    if p.returncode != 0:
        raise SystemExit(
            f"[outofcore-smoke] FAIL: {tag} baseline rc={p.returncode}"
        )
    rows_base = _load(base_out)
    if len(rows_base) != 3:
        raise SystemExit(
            f"[outofcore-smoke] FAIL: {tag} baseline wrote "
            f"{len(rows_base)} rows, expected 3"
        )
    st = _load(stats)
    want_disp = 1 if batch == "auto" else 0
    if st["cohort.dispatches"] != want_disp:
        raise SystemExit(
            f"[outofcore-smoke] FAIL: {tag} baseline "
            f"cohort.dispatches={st['cohort.dispatches']}, "
            f"expected {want_disp}"
        )
    if batch == "auto" and st["cohort.trajectories"] != 3:
        raise SystemExit(
            f"[outofcore-smoke] FAIL: cohort baseline batched "
            f"{st['cohort.trajectories']} trajectories, expected 3"
        )

    p = _run_child(
        work, ns, f"{tag}-kill", jdir_kill,
        os.path.join(work, f"unused_{tag}.json"), store, batch=batch,
        chaos=f"kill:prefetch:{chaos_count}",
    )
    if p.returncode != KILL_EXIT:
        raise SystemExit(
            f"[outofcore-smoke] FAIL: {tag} kill leg rc={p.returncode}, "
            f"expected {KILL_EXIT}"
        )
    n_recs = _journal_rows(jdir_kill)
    if n_recs != expect_journaled:
        raise SystemExit(
            f"[outofcore-smoke] FAIL: {tag} journal has {n_recs} rows "
            f"after kill:prefetch:{chaos_count}, "
            f"expected {expect_journaled}"
        )
    _validate_journal(jdir_kill, f"{tag}-kill")

    p = _run_child(work, ns, f"{tag}-resume", jdir_kill, res_out, store,
                   batch=batch, resume=True)
    if p.returncode != 0:
        raise SystemExit(
            f"[outofcore-smoke] FAIL: {tag} resume rc={p.returncode}"
        )
    _assert_rows_equal(rows_base, _load(res_out), f"{tag} kill->resume")
    print(f"[outofcore-smoke] {tag} kill->resume invariance: OK",
          file=sys.stderr)
    return rows_base


def orchestrate(ns) -> int:
    import tempfile

    work = tempfile.mkdtemp(prefix="eh-outofcore-")
    store = os.path.join(work, "store")

    # 1. per-trajectory dispatch: kill lands while the SECOND
    #    trajectory's prefetcher stages a window -> one full trajectory
    #    journaled, resume skips it
    fires = _fires_per_trajectory(ns)
    rows_seq = _kill_resume_story(
        work, ns, store, "seq", batch="off",
        chaos_count=fires + 2, expect_journaled=1,
    )

    # 2. cohort dispatch (the sweep default): one windowed cohort scan
    #    stages n_windows windows TOTAL, so the kill lands mid-cohort
    #    and NOTHING is journaled; resume re-trains the whole cohort
    rows_cohort = _kill_resume_story(
        work, ns, store, "cohort", batch="auto",
        chaos_count=2, expect_journaled=0,
    )
    if [r.get("label") for r in rows_cohort] != [
        r.get("label") for r in rows_seq
    ]:
        raise SystemExit(
            "[outofcore-smoke] FAIL: cohort sweep trained different "
            "trajectories than the per-trajectory sweep"
        )

    # 3. ring: faithful streamed+ring windows with real assignment
    #    halos; differing assignments never share a compiled scan
    ring_out = os.path.join(work, "rows_ring.json")
    ring_stats = os.path.join(work, "stats_ring.json")
    jdir_ring = os.path.join(work, "journal_ring")
    ring_store = os.path.join(work, "store_ring")
    p = _run_child(work, ns, "ring", jdir_ring, ring_out, ring_store,
                   batch="auto", ring=True, stats=ring_stats)
    if p.returncode != 0:
        raise SystemExit(
            f"[outofcore-smoke] FAIL: ring leg rc={p.returncode}"
        )
    rows_ring = _load(ring_out)
    if len(rows_ring) != 2:
        raise SystemExit(
            f"[outofcore-smoke] FAIL: ring leg wrote {len(rows_ring)} "
            f"rows, expected 2"
        )
    st = _load(ring_stats)
    if st["cohort.dispatches"] != 0:
        raise SystemExit(
            f"[outofcore-smoke] FAIL: ring trajectories with differing "
            f"assignments shared {st['cohort.dispatches']} cohort "
            f"dispatches, expected 0"
        )
    _validate_journal(jdir_ring, "ring")
    print("[outofcore-smoke] streamed+ring windowed sweep: OK",
          file=sys.stderr)

    print(json.dumps({
        "status": "PASS",
        "rows_seq": len(rows_seq),
        "rows_cohort": len(rows_cohort),
        "rows_ring": len(rows_ring),
        "workdir": work,
    }))
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--journal", default=None)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--out", default=None)
    ap.add_argument("--store", default=None)
    ap.add_argument("--batch", default="off", choices=["off", "auto"])
    ap.add_argument("--ring", action="store_true")
    ap.add_argument("--stats", default=None)
    ns = ap.parse_args()
    if ns.child:
        if not ns.journal or not ns.out or not ns.store:
            ap.error("--child needs --journal, --out and --store")
        return child(ns)
    return orchestrate(ns)


if __name__ == "__main__":
    sys.exit(main())
