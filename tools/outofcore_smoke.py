#!/usr/bin/env python
"""Out-of-core harness: shard-store -> streamed sweep -> kill -> resume
(``make outofcore-smoke``).

Proves the streamed-residency resilience contract end-to-end with REAL
process deaths, which the in-process tests cannot do:

  1. **baseline** — the child writes a shard store (data/store.py), opens
     it, and drives a journaled straggler sweep whose every trajectory
     runs ``stack_residency="streamed"`` with a multi-partition-window
     prefetch pipeline (stream_window=1 < P, so data/prefetch.py is on
     the hot path); the sweep runs to completion;
  2. **kill** — the same sweep with ``ERASUREHEAD_CHAOS=kill:prefetch:N``
     armed: the process dies (os._exit, preemption semantics) while the
     prefetcher stages a mid-run partition window — a kill mid-epoch of
     a streamed trajectory. N is sized so exactly one trajectory's row
     reached the journal first;
  3. **resume** — the same command with ``--resume`` reopens the SAME
     store directory (content digest -> identical journal keys), skips
     the journaled row, trains the rest, and must produce summary rows
     BITWISE identical to the baseline.

The journal is schema-checked with the same validator as every other
event log. Exit 0 = all invariants held.

Usage: python tools/outofcore_smoke.py [--rounds 8] [--workers 4]
       (the --child form is the harness's internal sweep runner)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

KILL_EXIT = 43  # erasurehead_tpu.utils.chaos.KILL_EXIT (no jax import here)


def child(ns) -> int:
    """One journaled STREAMED sweep run: the unit the orchestrator
    kills/resumes. The first child invocation writes the shard store;
    later ones (the resume leg) reopen it from disk, so the rehydration
    path crosses a real process boundary."""
    from erasurehead_tpu.data import store as store_lib
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.train import experiments
    from erasurehead_tpu.train import journal as journal_lib
    from erasurehead_tpu.utils.config import RunConfig

    W = ns.workers
    rows = W * 16
    if os.path.exists(os.path.join(ns.store, store_lib.META_NAME)):
        store = store_lib.open_store(ns.store)
    else:
        src = generate_gmm(rows, 8, n_partitions=W, seed=0)
        store = store_lib.write_store(src, ns.store, W)
    data = store.dataset()
    base = RunConfig(
        scheme="naive", n_workers=W, n_stragglers=0, num_collect=W // 2,
        rounds=ns.rounds, n_rows=rows, n_cols=8, lr_schedule=1.0,
        update_rule="GD", add_delay=True, seed=0, compute_mode="deduped",
        stack_residency="streamed", stream_window=1,
    )
    sweep = {
        "naive": [0],
        "cyccoded": [1],
        "avoidstragg": [1],
    }
    journal = journal_lib.SweepJournal(ns.journal, resume=ns.resume)
    try:
        summaries = experiments.straggler_sweep(
            base, data, sweep, journal=journal
        )
    finally:
        journal.close()
    with open(ns.out, "w") as f:
        json.dump(
            [journal_lib.science_row(s.row()) for s in summaries],
            f, indent=1,
        )
    return 0


def _fires_per_trajectory(ns) -> int:
    """Prefetch windows one streamed trajectory stages: the trainer's
    chunking arithmetic (trainer._train_streamed) with stream_window=1,
    so n_windows = P = workers and chunk length L = rounds // n_windows."""
    n_windows = ns.workers
    L = max(1, ns.rounds // n_windows)
    return len(range(0, ns.rounds, L))


def _run_child(workdir, ns, leg, journal_dir, out, store, resume=False,
               chaos=None) -> subprocess.CompletedProcess:
    cmd = [
        sys.executable, os.path.abspath(__file__), "--child",
        "--journal", journal_dir, "--out", out, "--store", store,
        "--rounds", str(ns.rounds), "--workers", str(ns.workers),
    ]
    if resume:
        cmd.append("--resume")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("ERASUREHEAD_CHAOS", None)
    if chaos:
        env["ERASUREHEAD_CHAOS"] = chaos
    print(f"[outofcore-smoke] {leg}: {' '.join(cmd[2:])}"
          + (f"  ERASUREHEAD_CHAOS={chaos}" if chaos else ""),
          file=sys.stderr)
    return subprocess.run(cmd, env=env, cwd=workdir)


def _load(path):
    with open(path) as f:
        return json.load(f)


def _assert_rows_equal(a, b, leg: str) -> None:
    if a == b:
        return
    for ra, rb in zip(a, b):
        if ra != rb:
            diff = {
                k: (ra.get(k), rb.get(k))
                for k in set(ra) | set(rb)
                if ra.get(k) != rb.get(k)
            }
            raise SystemExit(
                f"[outofcore-smoke] FAIL ({leg}): row {ra.get('label')!r} "
                f"differs from baseline: {diff}"
            )
    raise SystemExit(f"[outofcore-smoke] FAIL ({leg}): row sets differ")


def orchestrate(ns) -> int:
    import tempfile

    from erasurehead_tpu.obs import events as events_lib

    work = tempfile.mkdtemp(prefix="eh-outofcore-")
    store = os.path.join(work, "store")
    base_out = os.path.join(work, "rows_base.json")
    res_out = os.path.join(work, "rows_resumed.json")
    jdir_base = os.path.join(work, "journal_base")
    jdir_kill = os.path.join(work, "journal_kill")

    # 1. baseline: write the store, stream every trajectory, journaled
    p = _run_child(work, ns, "baseline", jdir_base, base_out, store)
    if p.returncode != 0:
        raise SystemExit(
            f"[outofcore-smoke] FAIL: baseline rc={p.returncode}"
        )
    rows_base = _load(base_out)
    if len(rows_base) != 3:
        raise SystemExit(
            f"[outofcore-smoke] FAIL: baseline wrote {len(rows_base)} "
            f"rows, expected 3"
        )

    # 2. kill while the SECOND trajectory's prefetcher stages a window
    #    (one full trajectory journaled, the next one mid-epoch)
    fires = _fires_per_trajectory(ns)
    p = _run_child(
        work, ns, "kill", jdir_kill, os.path.join(work, "unused.json"),
        store, chaos=f"kill:prefetch:{fires + 2}",
    )
    if p.returncode != KILL_EXIT:
        raise SystemExit(
            f"[outofcore-smoke] FAIL: kill leg rc={p.returncode}, "
            f"expected {KILL_EXIT}"
        )
    jpath = os.path.join(jdir_kill, "sweep_journal.jsonl")
    n_recs = sum(
        1 for line in open(jpath)
        if line.strip() and json.loads(line)["type"] == "sweep_trajectory"
    )
    if n_recs != 1:
        raise SystemExit(
            f"[outofcore-smoke] FAIL: journal has {n_recs} rows after "
            f"kill:prefetch:{fires + 2}, expected 1"
        )
    errors = events_lib.validate_file(jpath)
    if errors:
        raise SystemExit(
            f"[outofcore-smoke] FAIL: journal invalid: {errors}"
        )

    # 3. resume: reopen the store from disk, skip the journaled row,
    #    finish, match the baseline bitwise
    p = _run_child(
        work, ns, "resume", jdir_kill, res_out, store, resume=True
    )
    if p.returncode != 0:
        raise SystemExit(f"[outofcore-smoke] FAIL: resume rc={p.returncode}")
    _assert_rows_equal(rows_base, _load(res_out), "kill->resume")
    print("[outofcore-smoke] streamed kill->resume invariance: OK",
          file=sys.stderr)

    print(json.dumps({
        "status": "PASS",
        "rows": len(rows_base),
        "journaled_before_kill": n_recs,
        "workdir": work,
    }))
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--journal", default=None)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--out", default=None)
    ap.add_argument("--store", default=None)
    ns = ap.parse_args()
    if ns.child:
        if not ns.journal or not ns.out or not ns.store:
            ap.error("--child needs --journal, --out and --store")
        return child(ns)
    return orchestrate(ns)


if __name__ == "__main__":
    sys.exit(main())
