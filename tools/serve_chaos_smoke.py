#!/usr/bin/env python
"""Restart-under-load smoke with REAL process kills (`make serve-chaos-smoke`).

The crash-safety contract end-to-end, with the daemon as an actual
subprocess dying via ``os._exit`` (chaos ``kill:serve_dispatch:2`` — no
cleanup, no atexit, only what hit disk survives):

  leg 1  daemon (chaos-armed) serves one request to completion — the
         signature is WARM: executable journaled to JAX's on-disk
         compilation cache, row in the tenant journal, acceptance in the
         intake WAL. Two more same-signature requests arrive; their
         dispatch is invocation #2 of the serve_dispatch site -> the
         daemon DIES mid-dispatch (exit 43). The client's next result()
         raises the typed ServeUnavailableError, never a bare
         queue.Empty.
  leg 2  a fresh daemon starts on the same directories: the WAL replays
         (restart event: 3 records -> 1 rehydrated + 2 re-dispatched),
         the re-dispatch compiles against the on-disk cache, and the
         client resubmits all three requests -> every reply rehydrates
         (resumed=true) with rows BITWISE equal to leg 3's, and the
         compilation cache gained ZERO entries (0 recompiles of warm
         signatures).
  leg 3  an uninterrupted daemon in fresh directories serves the same
         three requests — the baseline the resubmitted rows must match
         byte-for-byte (science columns; volatile wall-clock keys
         excluded, train/journal.science_row).

Exit 0 = PASS (summary JSON on stdout); 1 = failure.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU relay

CFG = {
    "scheme": "naive", "n_workers": 4, "n_stragglers": 1, "rounds": 2,
    "n_rows": 64, "n_cols": 8, "lr_schedule": 0.5, "add_delay": True,
    "compute_mode": "deduped",
}
KILL_EXIT = 43  # utils/chaos.KILL_EXIT


def launch(sock, journal, cache, events, log, chaos=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("ERASUREHEAD_CHAOS", None)
    if chaos:
        env["ERASUREHEAD_CHAOS"] = chaos
    cmd = [
        sys.executable, "-m", "erasurehead_tpu.cli", "serve",
        "--socket", sock, "--journal-dir", journal,
        "--cache-dir", cache, "--events", events, "--window-ms", "50",
    ]
    out = open(log, "w")
    return subprocess.Popen(
        cmd, env=env, cwd=ROOT, stdout=out, stderr=subprocess.STDOUT
    )


def wait_socket(path, proc, timeout=300):
    """Wait until the daemon actually ACCEPTS on ``path`` — a killed
    daemon leaves a stale socket file behind, so existence alone lies."""
    import socket as socket_lib

    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path):
            probe = socket_lib.socket(
                socket_lib.AF_UNIX, socket_lib.SOCK_STREAM
            )
            try:
                probe.connect(path)
                return
            except OSError:
                pass
            finally:
                probe.close()
        if proc.poll() is not None:
            raise RuntimeError(
                f"daemon exited {proc.returncode} before listening"
            )
        time.sleep(0.2)
    raise RuntimeError(f"daemon never bound {path}")


def science(row):
    from erasurehead_tpu.train import journal as journal_lib

    return json.dumps(journal_lib.science_row(row), sort_keys=True)


def serve_three(sock, expect_resumed):
    """Submit the three requests and collect rows by label."""
    from erasurehead_tpu.serve.client import ServeClient

    c = ServeClient(sock)
    for label, seed in (("warm", 0), ("b", 1), ("c", 2)):
        c.submit("t", label, {**CFG, "seed": seed})
    rows = {}
    for _ in range(3):
        res = c.result(timeout=300)
        assert res["status"] == "ok", res
        if expect_resumed:
            assert res["resumed"], f"{res['label']} was not rehydrated"
        rows[res["label"]] = science(res["row"])
    c.close()
    return rows


def main() -> int:
    from erasurehead_tpu.obs import events as events_lib
    from erasurehead_tpu.serve.client import (
        ServeClient,
        ServeUnavailableError,
    )
    from erasurehead_tpu.train.cache import persistent_cache_entries

    base = tempfile.mkdtemp(prefix="eh-serve-chaos-")
    sock = os.path.join(base, "eh.sock")
    journal, cache = os.path.join(base, "journal"), os.path.join(base, "xla")
    ev1, ev2 = os.path.join(base, "ev1.jsonl"), os.path.join(base, "ev2.jsonl")

    # ---- leg 1: warm one signature, then die mid-dispatch --------------
    p1 = launch(sock, journal, cache, ev1, os.path.join(base, "d1.log"),
                chaos="kill:serve_dispatch:2")
    wait_socket(sock, p1)
    c = ServeClient(sock)
    c.submit("t", "warm", {**CFG, "seed": 0})
    res = c.result(timeout=300)
    assert res["status"] == "ok" and not res["resumed"], res
    # two more acceptances; their dispatch is serve_dispatch #2 -> kill
    c.submit("t", "b", {**CFG, "seed": 1})
    c.submit("t", "c", {**CFG, "seed": 2})
    rc = p1.wait(timeout=300)
    assert rc == KILL_EXIT, f"daemon exit {rc}, wanted chaos kill {KILL_EXIT}"
    try:
        c.result(timeout=30)
        raise AssertionError("dead daemon produced a result")
    except ServeUnavailableError as e:
        assert sock in str(e), e
    c.close()
    entries_before = persistent_cache_entries(cache)
    assert entries_before > 0, "warm leg wrote no on-disk cache entries"

    # ---- leg 2: restart on the same dirs, resubmit all -----------------
    if os.path.exists(sock):
        os.unlink(sock)  # the kill left a stale socket file behind
    p2 = launch(sock, journal, cache, ev2, os.path.join(base, "d2.log"))
    wait_socket(sock, p2)
    rows_restarted = serve_three(sock, expect_resumed=True)
    p2.terminate()
    p2.wait(timeout=60)
    entries_after = persistent_cache_entries(cache)
    new_compiles = entries_after - entries_before
    assert new_compiles == 0, (
        f"warm restart recompiled: {new_compiles} new cache entries"
    )
    restart_recs = [
        json.loads(line)
        for line in open(ev2)
        if line.strip() and json.loads(line).get("type") == "restart"
    ]
    assert restart_recs, "no restart event in the restarted daemon's log"
    assert restart_recs[0]["wal_records"] == 3, restart_recs
    assert restart_recs[0]["rehydrated"] >= 1, restart_recs
    assert events_lib.validate_file(ev2) == [], (
        events_lib.validate_file(ev2)
    )

    # ---- leg 3: uninterrupted baseline in fresh dirs -------------------
    base3 = tempfile.mkdtemp(prefix="eh-serve-chaos-base-")
    sock3 = os.path.join(base3, "eh.sock")
    p3 = launch(
        sock3, os.path.join(base3, "journal"), os.path.join(base3, "xla"),
        os.path.join(base3, "ev.jsonl"), os.path.join(base3, "d.log"),
    )
    wait_socket(sock3, p3)
    rows_baseline = serve_three(sock3, expect_resumed=False)
    p3.terminate()
    p3.wait(timeout=60)

    assert rows_restarted == rows_baseline, (
        "rehydrated rows differ from the uninterrupted baseline"
    )
    print(json.dumps({
        "status": "PASS",
        "wal_records": restart_recs[0]["wal_records"],
        "rehydrated": restart_recs[0]["rehydrated"],
        "resubmitted": restart_recs[0]["resubmitted"],
        "new_compile_cache_entries": new_compiles,
        "rows_bitwise_identical": True,
    }, indent=1))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"FAIL: {e}", file=sys.stderr)
        sys.exit(1)
