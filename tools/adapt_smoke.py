"""adapt-smoke: CPU regime-shift drive of the adaptive controller.

`make adapt-smoke` asserts, end to end:

  1. under a deterministic mid-run regime shift (adversarially slow
     worker, utils/chaos.REGIME_ENV grammar) the controller detects the
     shift and SWITCHES policy;
  2. every decision lands as a typed `adapt` event and the whole event
     log validates (tools/validate_events.py logic, obs/events.SCHEMA);
  3. decisions replay bitwise on a rerun (the kill→resume invariance:
     decisions are a pure function of seed + telemetry);
  4. telemetry-off runs stay bitwise-identical: the registry path with
     decode="fixed" and no capture produces the same trajectory as the
     instrumented run (the observation-only contract, extended over the
     scheme-registry refactor).
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from erasurehead_tpu import adapt  # noqa: E402
from erasurehead_tpu.data.synthetic import generate_gmm  # noqa: E402
from erasurehead_tpu.obs import events as obs_events  # noqa: E402
from erasurehead_tpu.parallel import straggler  # noqa: E402
from erasurehead_tpu.train import trainer  # noqa: E402
from erasurehead_tpu.utils.config import RunConfig  # noqa: E402

W, R, CHUNK = 6, 60, 5
OUT = "/tmp/eh-adapt-smoke"


def main() -> int:
    import jax

    os.makedirs(OUT, exist_ok=True)
    cfg = RunConfig(
        scheme="naive", n_workers=W, n_stragglers=1, rounds=R,
        n_rows=120, n_cols=8, lr_schedule=1.0, add_delay=True,
        compute_mode="deduped", update_rule="GD", seed=0,
    )
    ds = generate_gmm(120, 8, W, seed=0)
    shift = straggler.RegimeShift(
        kind="adversary", round=R // 2, worker=0, slowdown=8.0
    )
    arr = straggler.arrival_schedule(R, W, True, regime=shift)
    arms = [
        adapt.Arm("naive"),
        adapt.Arm("avoidstragg"),
        adapt.Arm("deadline", deadline=1.5),
    ]
    ctl = adapt.ControllerConfig(chunk_rounds=CHUNK, seed=0)

    # 1) regime-shift drive with event capture
    events_path = os.path.join(OUT, "events.jsonl")
    with obs_events.capture(events_path):
        res = adapt.train_adaptive(
            cfg, ds, arms=arms, controller=ctl, arrivals=arr
        )
    reasons = [d["reason"] for d in res.decisions]
    arms_seq = [d["arm"] for d in res.decisions]
    switches = sum(1 for a, b in zip(arms_seq, arms_seq[1:]) if a != b)
    assert "regime_shift" in reasons, (
        f"controller never detected the regime shift: {reasons}"
    )
    assert switches >= 1, f"controller never switched policy: {arms_seq}"
    print(
        f"adapt-smoke: {len(res.decisions)} decisions, {switches} "
        f"switches, shift detected at chunk "
        f"{reasons.index('regime_shift')}, controller overhead "
        f"{1000 * res.decision_overhead_s:.1f} ms"
    )

    # 2) the event log validates, adapt records included
    with open(events_path) as f:
        lines = f.readlines()
    errors = obs_events.validate_lines(lines)
    assert not errors, "event log invalid:\n" + "\n".join(errors)
    adapt_recs = [
        json.loads(line)
        for line in lines
        if json.loads(line).get("type") == "adapt"
    ]
    assert len(adapt_recs) == len(res.decisions)
    print(f"adapt-smoke: {len(adapt_recs)} adapt events validate")

    # 3) decision replay: rerunning the same seed + arrivals reproduces
    # the decision sequence and the trained parameters bitwise
    res2 = adapt.train_adaptive(
        cfg, ds, arms=arms, controller=ctl, arrivals=arr
    )
    assert res.decisions == res2.decisions, "decision replay diverged"
    for a, b in zip(
        jax.tree.leaves(res.result.final_params),
        jax.tree.leaves(res2.result.final_params),
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    print("adapt-smoke: decision + parameter replay bitwise OK")

    # 4) telemetry-off bitwise: a plain (non-adaptive) run of the same
    # config through the registry path is identical with and without an
    # event capture — the observation-only contract over the refactor
    plain_cfg = cfg
    with obs_events.capture(os.path.join(OUT, "plain_events.jsonl")):
        instrumented = trainer.train(
            plain_cfg, ds, arrivals=arr, measure=False
        )
    dark = trainer.train(plain_cfg, ds, arrivals=arr, measure=False)
    for a, b in zip(
        jax.tree.leaves(instrumented.params_history),
        jax.tree.leaves(dark.params_history),
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            "telemetry on/off trajectories differ"
        )
    assert np.array_equal(instrumented.timeset, dark.timeset)
    print("adapt-smoke: telemetry on/off bitwise-identical")
    print(f"adapt-smoke: OK (events -> {events_path})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
