#!/usr/bin/env python
"""Chaos harness: kill→resume and cohort-degradation cycles for the sweep
runner (``make chaos-smoke``).

Drives the resilience contract end-to-end with REAL process deaths, which
the in-process tests cannot do:

  1. **baseline** — a small straggler sweep (journaled) runs to completion;
  2. **kill** — the same sweep with ``ERASUREHEAD_CHAOS=kill:trajectory:2``
     armed: the child process dies (os._exit, preemption semantics) right
     after its 2nd trajectory row hits the journal;
  3. **resume** — the same command with ``--resume`` picks the journal up,
     skips the 2 completed trajectories, trains the rest, and must produce
     summary rows IDENTICAL to the baseline (labels, simulated clocks,
     losses bitwise-equal, decode-error columns — train/journal.science_row
     drops only the run-local wall-clock/cache telemetry);
  4. **degrade** — ``ERASUREHEAD_CHAOS=raise:cohort:1+`` fails every
     trajectory-batched cohort dispatch, forcing bisection down to
     sequential train(); the sweep must still complete with rows identical
     to the baseline.

The journal file is schema-checked with the same validator as every other
event log. Exit 0 = all invariants held.

Usage: python tools/chaos_sweep.py [--rounds 4] [--workers 4]
       (the --child form is the harness's internal sweep runner)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

KILL_EXIT = 43  # erasurehead_tpu.utils.chaos.KILL_EXIT (no jax import here)


def child(ns) -> int:
    """One journaled sweep run: the unit the orchestrator kills/resumes."""
    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.train import experiments
    from erasurehead_tpu.train import journal as journal_lib
    from erasurehead_tpu.utils.config import RunConfig

    W = ns.workers
    rows = W * 16
    base = RunConfig(
        scheme="naive", n_workers=W, n_stragglers=0, num_collect=W // 2,
        rounds=ns.rounds, n_rows=rows, n_cols=8, lr_schedule=1.0,
        update_rule="AGD", add_delay=True, seed=0, compute_mode="deduped",
    )
    data = generate_gmm(rows, 8, n_partitions=W, seed=0)
    sweep = {
        "naive": [0],
        "avoidstragg": [1, 2],
        "approx": [1],
        "cyccoded": [1],
    }
    journal = journal_lib.SweepJournal(ns.journal, resume=ns.resume)
    try:
        summaries = experiments.straggler_sweep(
            base, data, sweep, batch=ns.batch, journal=journal
        )
    finally:
        journal.close()
    with open(ns.out, "w") as f:
        json.dump(
            [journal_lib.science_row(s.row()) for s in summaries],
            f, indent=1,
        )
    return 0


def _run_child(workdir, ns, leg, journal_dir, out, resume=False,
               chaos=None, batch="auto") -> subprocess.CompletedProcess:
    cmd = [
        sys.executable, os.path.abspath(__file__), "--child",
        "--journal", journal_dir, "--out", out,
        "--rounds", str(ns.rounds), "--workers", str(ns.workers),
        "--batch", batch,
    ]
    if resume:
        cmd.append("--resume")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("ERASUREHEAD_CHAOS", None)
    if chaos:
        env["ERASUREHEAD_CHAOS"] = chaos
    print(f"[chaos-sweep] {leg}: {' '.join(cmd[2:])}"
          + (f"  ERASUREHEAD_CHAOS={chaos}" if chaos else ""),
          file=sys.stderr)
    return subprocess.run(cmd, env=env, cwd=workdir)


def _load(path):
    with open(path) as f:
        return json.load(f)


def _assert_rows_equal(a, b, leg: str) -> None:
    if a == b:
        return
    for ra, rb in zip(a, b):
        if ra != rb:
            diff = {
                k: (ra.get(k), rb.get(k))
                for k in set(ra) | set(rb)
                if ra.get(k) != rb.get(k)
            }
            raise SystemExit(
                f"[chaos-sweep] FAIL ({leg}): row {ra.get('label')!r} "
                f"differs from baseline: {diff}"
            )
    raise SystemExit(f"[chaos-sweep] FAIL ({leg}): row sets differ")


def orchestrate(ns) -> int:
    import tempfile

    from erasurehead_tpu.obs import events as events_lib

    work = tempfile.mkdtemp(prefix="eh-chaos-")
    base_out = os.path.join(work, "rows_base.json")
    res_out = os.path.join(work, "rows_resumed.json")
    deg_out = os.path.join(work, "rows_degraded.json")
    jdir_base = os.path.join(work, "journal_base")
    jdir_kill = os.path.join(work, "journal_kill")
    jdir_deg = os.path.join(work, "journal_degrade")

    # 1. baseline (journaled, uninterrupted)
    p = _run_child(work, ns, "baseline", jdir_base, base_out)
    if p.returncode != 0:
        raise SystemExit(f"[chaos-sweep] FAIL: baseline rc={p.returncode}")
    rows_base = _load(base_out)

    # 2. kill after the 2nd journaled trajectory (preemption semantics)
    p = _run_child(
        work, ns, "kill", jdir_kill, os.path.join(work, "unused.json"),
        chaos="kill:trajectory:2",
    )
    if p.returncode != KILL_EXIT:
        raise SystemExit(
            f"[chaos-sweep] FAIL: kill leg rc={p.returncode}, "
            f"expected {KILL_EXIT}"
        )
    jpath = os.path.join(jdir_kill, "sweep_journal.jsonl")
    n_recs = sum(
        1 for line in open(jpath)
        if line.strip() and json.loads(line)["type"] == "sweep_trajectory"
    )
    if n_recs != 2:
        raise SystemExit(
            f"[chaos-sweep] FAIL: journal has {n_recs} rows after "
            f"kill:trajectory:2, expected 2"
        )
    errors = events_lib.validate_file(jpath)
    if errors:
        raise SystemExit(f"[chaos-sweep] FAIL: journal invalid: {errors}")

    # 3. resume: skip the 2 journaled rows, finish, match the baseline
    p = _run_child(work, ns, "resume", jdir_kill, res_out, resume=True)
    if p.returncode != 0:
        raise SystemExit(f"[chaos-sweep] FAIL: resume rc={p.returncode}")
    _assert_rows_equal(rows_base, _load(res_out), "kill->resume")
    print("[chaos-sweep] kill->resume invariance: OK", file=sys.stderr)

    # 4. every cohort dispatch fails -> bisect to sequential, same rows
    p = _run_child(
        work, ns, "degrade", jdir_deg, deg_out, chaos="raise:cohort:1+",
        batch="on",
    )
    if p.returncode != 0:
        raise SystemExit(f"[chaos-sweep] FAIL: degrade rc={p.returncode}")
    _assert_rows_equal(rows_base, _load(deg_out), "cohort-degradation")
    print("[chaos-sweep] cohort-degradation invariance: OK",
          file=sys.stderr)

    print(json.dumps({
        "status": "PASS",
        "rows": len(rows_base),
        "workdir": work,
    }))
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--journal", default=None)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--out", default=None)
    ap.add_argument("--batch", default="auto",
                    choices=["on", "off", "auto"])
    ns = ap.parse_args()
    if ns.child:
        if not ns.journal or not ns.out:
            ap.error("--child needs --journal and --out")
        return child(ns)
    return orchestrate(ns)


if __name__ == "__main__":
    sys.exit(main())
