"""tune-smoke: CPU end-to-end drive of the measured autotuning plane.

`make tune-smoke` asserts, end to end:

  1. a cold `block_decode` race (fused per-leaf decode vs treewise
     pack-then-einsum, blockwise coding on) runs to a verdict and
     persists it to a fresh decision cache;
  2. the cache is DETERMINISTIC: re-racing the identical shape with the
     identical seeds into a second fresh cache produces a byte-identical
     file (the cache stores choices only — no timings, no timestamps);
  3. a subsequent block_decode="auto" training run resolves the knob
     from the cache (a `tune` event with source="cache") without
     re-racing, and warm resolution costs < 1 ms;
  4. the resolution is observation-only: the tuned `auto` run's
     parameter trajectory is bitwise-identical to the forced runs
     (fused == treewise == auto — the knob is pure lowering), with
     telemetry on or off;
  5. a run chaos-killed at the head of the race (ERASUREHEAD_CHAOS
     kill:tune_race:1, exit code chaos.KILL_EXIT) leaves NO cache file
     (atomic writes — never a torn one), and the cold re-run (a fresh
     subprocess, cold JIT caches) races to a complete canonical verdict
     under the SAME decision key — the kill is invisible in the cache's
     structure. (The cold process's wall-clock timings are its own, so
     a within-tie-margin verdict may legitimately settle on the other
     candidate; byte-identity is asserted between the two SAME-process
     races in step 2, and exactly — with a scripted clock — in
     tests/test_tune.py.);
  6. every emitted `tune` event passes the events schema validator.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from erasurehead_tpu import tune as tune_lib  # noqa: E402
from erasurehead_tpu.data.synthetic import generate_gmm  # noqa: E402
from erasurehead_tpu.obs import events as obs_events  # noqa: E402
from erasurehead_tpu.tune import races as tune_races  # noqa: E402
from erasurehead_tpu.utils import chaos  # noqa: E402
from erasurehead_tpu.utils.config import RunConfig  # noqa: E402

OUT = "/tmp/eh-tune-smoke"


def _leaves(result):
    import jax

    return [np.asarray(x) for x in jax.tree.leaves(result.final_params)]


def _use_cache(path):
    os.environ[tune_lib.ENV_PATH] = path
    tune_lib.reset()
    tune_lib.reset_emitted()


def main() -> int:
    from erasurehead_tpu.train import trainer

    os.makedirs(OUT, exist_ok=True)
    cfg = RunConfig(
        scheme="approx", model="deepmlp", n_workers=8, n_stragglers=1,
        num_collect=6, rounds=4, n_rows=256, n_cols=32,
        update_rule="AGD", lr_schedule=0.5, add_delay=True, seed=0,
        layer_coding="on",
    )
    ds = generate_gmm(cfg.n_rows, cfg.n_cols, n_partitions=cfg.n_workers, seed=0)

    # 1. cold race into a fresh cache
    cache_a = os.path.join(OUT, "cache_a.json")
    for p in (cache_a,):
        if os.path.exists(p):
            os.unlink(p)
    _use_cache(cache_a)
    res = tune_races.race_block_decode(cfg, ds, reps=2)
    assert os.path.exists(cache_a), "race did not persist a cache"
    print(
        f"tune-smoke: cold race -> choice={res.choice} "
        f"({'decisive' if res.decisive else 'tie -> fallback'}) "
        f"timings={ {k: round(v * 1e3, 2) for k, v in sorted(res.timings.items())} }ms"
    )

    # 2. determinism: identical re-race -> byte-identical cache file
    cache_b = os.path.join(OUT, "cache_b.json")
    if os.path.exists(cache_b):
        os.unlink(cache_b)
    _use_cache(cache_b)
    tune_races.race_block_decode(cfg, ds, reps=2)
    bytes_a = open(cache_a, "rb").read()
    bytes_b = open(cache_b, "rb").read()
    assert bytes_a == bytes_b, (
        f"re-raced cache differs:\n{bytes_a!r}\nvs\n{bytes_b!r}"
    )
    print(f"tune-smoke: re-race byte-identical ({len(bytes_a)} bytes)")

    # 3. warm resolution: auto resolves from the cache, < 1 ms, no re-race
    _use_cache(cache_a)
    auto_cfg = dataclasses.replace(cfg, block_decode="auto")
    ev_path = os.path.join(OUT, "events.jsonl")
    with obs_events.capture(ev_path):
        r_auto = trainer.train(auto_cfg, ds)
    tune_evs = [
        json.loads(line)
        for line in open(ev_path)
        if line.strip() and json.loads(line).get("type") == "tune"
    ]
    cached = [
        e for e in tune_evs
        if e["race"] == "block_decode" and e["source"] == "cache"
    ]
    assert cached and cached[0]["choice"] == res.choice, (
        f"auto did not resolve block_decode from the cache: {tune_evs}"
    )
    model, X = trainer.resolved_stack(auto_cfg, ds)
    sig = tune_lib.run_shape_signature(model, X)
    t0 = time.perf_counter()
    for _ in range(20):
        tune_lib.lookup("block_decode", sig)
    warm_s = (time.perf_counter() - t0) / 20
    assert warm_s < 1e-3, f"warm resolution too slow: {warm_s * 1e3:.3f}ms"
    print(
        f"tune-smoke: auto resolved '{cached[0]['choice']}' from cache, "
        f"warm lookup {warm_s * 1e6:.1f}us"
    )

    # 4. observation-only: fused == treewise == tuned auto, bitwise;
    #    and the tuned run with telemetry off matches the captured one
    r_fused = trainer.train(
        dataclasses.replace(cfg, block_decode="fused"), ds
    )
    r_tree = trainer.train(
        dataclasses.replace(cfg, block_decode="treewise"), ds
    )
    r_dark = trainer.train(auto_cfg, ds)
    for name, other in (
        ("fused", r_fused), ("treewise", r_tree), ("auto-dark", r_dark)
    ):
        assert all(
            (a == b).all() for a, b in zip(_leaves(r_auto), _leaves(other))
        ), f"tuned auto run != {name} run (must be bitwise)"
    print("tune-smoke: fused == treewise == auto, telemetry on/off bitwise")

    # 5. chaos kill mid-race: no cache file, cold re-run same verdict
    cache_c = os.path.join(OUT, "cache_c.json")
    if os.path.exists(cache_c):
        os.unlink(cache_c)
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        tune_lib.ENV_PATH: cache_c,
        chaos.CHAOS_ENV: "kill:tune_race:1",
    })
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "erasurehead_tpu.cli", "tune",
         "--race", "block_decode", "--rounds", "4", "--rows", "256",
         "--cols", "32", "--reps", "2"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == chaos.KILL_EXIT, (
        f"chaos kill did not fire: rc={proc.returncode}\n{proc.stderr}"
    )
    assert not os.path.exists(cache_c), (
        "killed race left a cache file (writes must be atomic, and the "
        "kill fires before any candidate is timed)"
    )
    env.pop(chaos.CHAOS_ENV)
    proc = subprocess.run(
        [sys.executable, "-m", "erasurehead_tpu.cli", "tune",
         "--race", "block_decode", "--rounds", "4", "--rows", "256",
         "--cols", "32", "--reps", "2"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, f"cold re-run failed:\n{proc.stderr}"
    bytes_c = open(cache_c, "rb").read()
    doc_c = json.loads(bytes_c)
    decisions_c = {
        k: v["choice"] for k, v in doc_c["decisions"].items()
    }
    assert bytes_c == tune_lib.canonical_bytes(decisions_c), (
        "cold re-run cache is not canonically serialized"
    )
    assert set(decisions_c) == set(
        json.loads(bytes_a)["decisions"]
    ), "cold re-run decided under a different key than the in-process race"
    (choice_c,) = decisions_c.values()
    assert choice_c in tune_lib.TUNE_CHOICES["block_decode"], choice_c
    print(
        f"tune-smoke: chaos kill (rc={chaos.KILL_EXIT}) left no cache; "
        f"cold re-run raced to a complete verdict ({choice_c}) under the "
        f"same key"
    )

    # 6. the emitted tune events validate
    errors = obs_events.validate_lines(open(ev_path))
    assert not errors, f"event validation failed: {errors[:5]}"
    print(f"tune-smoke: {len(tune_evs)} tune event(s) validate")

    print("tune-smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
