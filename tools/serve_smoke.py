#!/usr/bin/env python
"""Smoke-check the multi-tenant serve daemon on CPU (`make serve-smoke`).

Starts the daemon in-process, races 4 client threads whose requests share
a cohort signature (overlapping shapes, per-client seeds), then asserts
the serving contract:

  - packing happened: serve.dispatches < serve.requests (the clients'
    trajectories shared compiled dispatches instead of going one-by-one),
    and cohort.dispatches agrees;
  - bitwise row equality: the same requests run SEQUENTIALLY through the
    daemon (one at a time, same fixed dispatch width) produce science
    rows identical byte-for-byte, tolerating only completion order —
    packing is a throughput lever, never a numerics knob;
  - per-tenant journals landed (one sweep_journal.jsonl per tenant) and
    pass the schema check, as does the daemon's own event log
    (request/pack/admit records included);
  - `erasurehead-tpu report` renders the serve section without error.

Exit 0 = all assertions hold; 1 = failure (printed).
"""

import json
import os
import sys
import tempfile
import threading

# runnable from anywhere without an install (the tools/ convention)
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU relay


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from erasurehead_tpu.data.synthetic import generate_gmm
    from erasurehead_tpu.obs import events as events_lib
    from erasurehead_tpu.obs import report as report_lib
    from erasurehead_tpu.obs.metrics import REGISTRY
    from erasurehead_tpu.serve import server as serve_server
    from erasurehead_tpu.train import journal as journal_lib
    from erasurehead_tpu.utils.config import RunConfig

    W, rounds, n_clients = 8, 4, 4
    data = generate_gmm(W * 16, 24, n_partitions=W, seed=0)
    common = dict(
        n_workers=W, n_stragglers=1, rounds=rounds, n_rows=W * 16,
        n_cols=24, update_rule="AGD", lr_schedule=0.5, add_delay=True,
        compute_mode="deduped",
    )
    schemes = [
        ("naive", {}),
        ("cyccoded", {}),
        ("approx", {"num_collect": 6}),
        ("deadline", {"deadline": 1.0}),
    ]
    requests = [
        (
            f"tenant{k}",
            f"{s}_c{k}",
            RunConfig(**{**common, **extra, "scheme": s, "seed": k}),
        )
        for k in range(n_clients)
        for s, extra in schemes
    ]
    n_requests = len(requests)
    width = 16  # fixed dispatch width shared by both runs

    def science(summary):
        return json.dumps(
            journal_lib.science_row(journal_lib.summary_payload(summary)),
            sort_keys=True,
        )

    workdir = tempfile.mkdtemp(prefix="eh-serve-smoke-")
    events_path = os.path.join(workdir, "serve_events.jsonl")
    journal_dir = os.path.join(workdir, "journal")

    for c in ("serve.requests", "serve.dispatches", "serve.results",
              "cohort.dispatches"):
        REGISTRY.counter(c).reset()

    # ---- packed: 4 concurrent clients, shared dispatches -----------------
    with events_lib.capture(events_path):
        with serve_server.serving(
            window_s=0.2, max_cohort=width, journal_dir=journal_dir
        ) as srv:
            handles, hlock = [], threading.Lock()

            def client(tenant: str) -> None:
                for tn, label, cfg in requests:
                    if tn != tenant:
                        continue
                    h = srv.submit(
                        tenant=tn, label=label, config=cfg, dataset=data
                    )
                    with hlock:
                        handles.append(h)

            threads = [
                threading.Thread(target=client, args=(f"tenant{k}",))
                for k in range(n_clients)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            packed = [h.result(timeout=300) for h in handles]

    dispatches = REGISTRY.counter("serve.dispatches").value
    n_req_counter = REGISTRY.counter("serve.requests").value
    cohort_dispatches = REGISTRY.counter("cohort.dispatches").value
    packed_rows = sorted(science(r.summary) for r in packed)

    # ---- sequential: same requests, one at a time, fresh journal ---------
    with serve_server.serving(
        window_s=0.001, max_cohort=width,
        journal_dir=os.path.join(workdir, "journal-seq"),
    ) as srv:
        seq_rows = sorted(
            science(
                srv.submit(
                    tenant=tn, label=label, config=cfg, dataset=data
                ).result(timeout=300).summary
            )
            for tn, label, cfg in requests
        )

    failures = []
    statuses = {r.status for r in packed}
    if statuses != {"ok"}:
        failures.append(f"expected all-ok results, got statuses {statuses}")
    if n_req_counter != n_requests:
        failures.append(
            f"serve.requests={n_req_counter} != {n_requests} submitted"
        )
    if dispatches >= n_requests:
        failures.append(
            f"serve.dispatches={dispatches} not < {n_requests} requests: "
            "the daemon did not pack"
        )
    if cohort_dispatches > dispatches:
        failures.append(
            f"cohort.dispatches={cohort_dispatches} exceeds "
            f"serve.dispatches={dispatches}"
        )
    if packed_rows != seq_rows:
        n_diff = sum(1 for a, b in zip(packed_rows, seq_rows) if a != b)
        failures.append(
            f"packed vs sequential science rows differ ({n_diff} of "
            f"{n_requests}): packing changed the numbers"
        )
    schema_errors = events_lib.validate_file(events_path)
    failures.extend(f"serve events schema: {e}" for e in schema_errors)
    for k in range(n_clients):
        jpath = os.path.join(
            journal_dir, f"tenant{k}", journal_lib.JOURNAL_NAME
        )
        if not os.path.exists(jpath):
            failures.append(f"missing per-tenant journal {jpath}")
            continue
        errs = events_lib.validate_file(jpath)
        failures.extend(f"journal tenant{k}: {e}" for e in errs)
    rendered = report_lib.render([events_path])
    if "serve (multi-tenant cohort packing)" not in rendered:
        failures.append("report did not render the serve section")

    print(
        f"serve-smoke: {n_requests} requests from {n_clients} tenants -> "
        f"{dispatches} dispatch(es); rows bitwise vs sequential: "
        f"{packed_rows == seq_rows}"
    )
    print(f"events -> {events_path}")
    print(rendered.split("serve (multi-tenant")[-1] if failures == [] else "")
    for f in failures:
        print(f"FAIL: {f}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
