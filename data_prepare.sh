#!/usr/bin/env bash
# Dataset preparation — the TPU equivalent of the reference's data_prepare.sh
# (data_prepare.sh:23): featurize a real dataset and write the partitioned
# reference on-disk layout so runs can load per-worker shards.
#
# Usage: bash data_prepare.sh [dataset] [source_dir] [n_workers]
set -euo pipefail

DATASET="${1:-kc_house_data}"
SOURCE="${2:-./straggdata/raw}"
N_WORKERS="${3:-30}"
OUT=./straggdata

exec python -m erasurehead_tpu.data.prepare real \
  --dataset "$DATASET" --source "$SOURCE" --workers "$N_WORKERS" --out "$OUT"
